"""Streaming rebalance with warm start — the BASELINE config-5 loop.

The reference is stateless across generations (SURVEY §2.4.8): every
rebalance re-solves from scratch, so two consecutive rebalances under
slightly drifted lags can reshuffle many partitions (assignment churn =
state invalidation for the consumers).  The streaming engine keeps the
previous choice vector as a warm start (SURVEY §5 checkpoint/resume row —
the optional warm start for the streaming-rebalance benchmark):

* **cold start / shape change / guardrail trip** — full solve with the
  transfer-lean :func:`..ops.batched.assign_stream` path plus a
  quality-refinement pass (churn is unbounded on cold paths anyway, and
  refining makes a guardrail trip actually restore near-bound quality
  rather than resetting to plain greedy's slack).  When the active mesh
  manager elects the P-axis-sharded backend for the shape
  (:func:`..ops.dispatch.sharded_solve_manager` — ``sharded/``), ONE
  sharded seed+refine dispatch serves the cold solve instead and the
  resident state rebuilds lazily from its choice (the
  :meth:`StreamingAssignor.seed_choice` contract); any sharded failure
  degrades the manager and falls back single-device in-request;
* **warm rebalance** — keep the previous assignment; first evaluate its
  quality under the NEW lags host-side (one weighted bincount, ~1 ms at
  P=100k).  If the max/mean imbalance is still within
  ``refine_threshold`` of the input-driven bound, the epoch is a
  **no-op**: zero churn, zero device traffic — a rebalance that would
  move nothing should cost nothing (the reference re-solves O(P*C) every
  time regardless).  Otherwise ONE fused device dispatch
  (:func:`_warm_fused_resident`) does the whole epoch's quality work:
  re-derive the per-consumer totals under the new lags from the
  device-resident row table (the fused equivalent of the bincount), test
  them against the quality target, and run the multi-round resident
  exchange-refinement loop (:func:`..ops.refine.refine_rounds_resident`
  — a ``lax.while_loop`` whose condition early-exits on target-met /
  stagnant-peak / budget-spent) entirely on device.  The count invariant
  is preserved by construction, imbalance is re-tightened, and only the
  exchanges' partitions move — ``refine_iters`` is a total *exchange
  budget* accounted per APPLIED exchange, so churn is bounded by
  2 x refine_iters instead of O(P) while a concentrated drift can spend
  the whole budget on one stubborn peak across many cheap rounds.

  The fused dispatch is transfer-lean AND compute-lean: the previous
  choice vector, the [C, M] row table, and the counts live
  **device-resident** between dispatches as DONATED buffers (they are
  the engine's own state — re-uploading or rebuilding them every epoch
  would dominate the dispatch), lags upload as int32 when their range
  allows (as the cold path does), and the validity mask is derived on
  device from the static shape, so the round trip carries only the new
  lag vector in and the narrow choice out.  Executables are cached per
  (P-bucket, C, budget) signature — warm them via :mod:`..warmup`'s
  stream job so the steady-state loop compiles NOTHING (asserted by the
  bench's ``warm_compile_count`` gate).

* **membership change** — :meth:`StreamingAssignor.remap_members` carries
  the warm state across a join/leave (the usual rebalance trigger, where
  the stateless reference reshuffles O(P) partitions): surviving members
  keep their partitions, a host-side repair pass re-seats only orphaned
  rows and capacity overflow (count-primary greedy over the moving rows),
  and the exchange refinement re-tightens balance — churn bounded by
  ``repaired_rows + 2 * refine_iters``.

* **delta epochs** — steady-state drift touches a small fraction of
  partitions per epoch, yet a dense warm dispatch re-uploads the whole
  ``[P]`` lag vector; at scale the H2D upload, not the refine, is the
  binding per-wave cost (the FlashSinkhorn IO-vs-compute argument,
  applied to the *input* instead of the operands).  The resident warm
  state therefore carries the padded int64 lag vector as a FOURTH
  device-resident donated buffer, and the engine keeps a host-side
  mirror of what that buffer holds.  When the epoch's changed fraction
  is small enough (``delta_max_fraction``, and the pow2-padded ``[K]``
  index/value update is strictly fewer bytes than the dense payload),
  :func:`_warm_fused_delta` scatter-applies the delta to the resident
  lag buffer and runs the SAME warm refine core in the same dispatch —
  bit-identical to the dense path by construction (the scattered buffer
  holds the identical int64 values).  K pads to a bounded pow2 ladder
  (``DELTA_MIN_K`` .. ``DELTA_MIN_K << (delta_buckets - 1)``, one
  executable per rung — warm them via :mod:`..warmup`); padding entries
  write index 0's NEW value, so they are no-ops even when index 0 is
  itself part of the delta.  Fallbacks are automatic and dense: changed
  fraction over the threshold, a failed divergence check (the device
  totals' sum must equal the host lag sum — the assignment-invariant
  conservation law), an injected ``delta.apply``/``delta.diff`` fault,
  or any host state that predates the resident buffer (roster churn,
  :meth:`StreamingAssignor.seed_choice` recovery, shape change) — the
  dense dispatch re-seeds the resident lag buffer and the next epoch
  re-enters delta mode.  ``klba_h2d_bytes_total{path=dense|delta}`` and
  ``klba_delta_epochs_total{outcome=applied|fallback|resync}`` count
  the trade; the ``stream.h2d_delta`` span times the delta staging.

The churn/quality trade-off is configurable per rebalance via
``refine_iters``.
"""

from __future__ import annotations

import contextlib
import functools
import logging
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import faults, metrics
from ..utils import scrub as scrub_mod
from ..utils import trace as trace_mod
from ..utils.observability import count_constrained_bound
from ..utils.watchdog import capture_abandon_check
from .batched import _narrow_choice, _stream_device, assign_stream, stream_payload
from .delta import apply_assignment_delta, compact_changed, readback_k
from .dispatch import ensure_x64, observe_pack_shift
from .packing import pad_bucket, pad_chunk, table_rows
from .refine import build_choice_tables, refine_rounds_resident

LOGGER = logging.getLogger(__name__)

# Delta-epoch K ladder: a sparse (indices, values) update pads to a pow2
# K bucket so the executable count stays bounded — DELTA_MIN_K is the
# smallest rung, and an engine's ladder tops out at
# ``DELTA_MIN_K << (delta_buckets - 1)`` (one executable per rung,
# warmed by ..warmup's stream job).  Per-entry upload cost: int32 index
# + int64 value.
DELTA_MIN_K = 16
_DELTA_ENTRY_BYTES = 4 + 8

# Adaptive-cutoff tuning (StreamingAssignor.delta_adaptive): window of
# observed per-epoch changed fractions, the sample floor below which
# the global knob serves unchanged, the quantile the cutoff tracks, and
# its safety margin — q90 * 1.5 keeps the stream's routine epochs
# inside the cutoff while anomalous spikes (churn storms, resyncs)
# fall back dense.
_ADAPT_WINDOW = 64
_ADAPT_MIN_SAMPLES = 8
_ADAPT_QUANTILE = 0.9
_ADAPT_MARGIN = 1.5


def delta_bucket(n_changed: int) -> int:
    """Pow2 K bucket a delta of ``n_changed`` entries pads to."""
    n = max(int(n_changed), 1)
    if n <= DELTA_MIN_K:
        return DELTA_MIN_K
    return 1 << (n - 1).bit_length()


def delta_k_ladder(buckets: int) -> list:
    """The bounded K ladder for ``buckets`` rungs (warm-up drives one
    synthetic delta wave per rung so the serving path compiles
    nothing)."""
    return [DELTA_MIN_K << i for i in range(max(int(buckets), 0))]


@dataclass
class StreamingStats:
    cold_start: bool = False
    guardrail_tripped: bool = False  # warm quality fell past the guardrail
    refined: bool = False  # a device refine dispatch ran this epoch
    churn: int = 0  # partitions whose consumer changed vs previous epoch
    repaired_rows: int = 0  # rows re-seated by the membership repair pass
    max_mean_imbalance: float = 1.0
    imbalance_bound: float = 1.0  # input-driven lower bound max_lag/mean
    count_spread: int = 0
    refine_rounds: int = 0  # resident-refine rounds the fused dispatch ran
    refine_exchanges: int = 0  # exchanges it applied (churn <= 2x this)
    # The delta/dense cutoff actually in force this epoch (equals the
    # global delta_max_fraction until the adaptive window has enough
    # samples — see StreamingAssignor.delta_adaptive).
    delta_effective_fraction: float = 0.0
    sharded_solve: bool = False  # this epoch's cold solve ran P-sharded

    @property
    def quality_ratio(self) -> float:
        """Achieved imbalance normalized to the input-driven bound —
        THE definition (shared by the engine's telemetry, the wire
        response, and the flight records; same normalization as
        RebalanceStats.quality_ratio and the bench)."""
        return self.max_mean_imbalance / max(self.imbalance_bound, 1.0)


def _pad_choice(choice, B: int):
    """Trace-time helper: padded int32[B] view of a choice vector that is
    either already the padded device-resident buffer or an exact-shape
    host start."""
    if choice.shape[0] == B and choice.dtype == jnp.int32:
        return choice
    P = choice.shape[0]
    return jnp.pad(choice.astype(jnp.int32), (0, B - P), constant_values=-1)


def _state_digest(lags_p, choice_p, counts, num_consumers: int,
                  row_tab=None):
    """Device-computed integrity digest of the resident state — int64[5]
    ``[counts_sum, range_violations, lags_sum, counts_vs_choice_L1,
    row_tab_checksum]`` (see :mod:`..utils.scrub` for the host truths
    each slot must match; the fifth lane audits the [C, M] row TABLE
    slot-by-slot and is int64[4]-compatible when ``row_tab`` is not
    passed).  Fused into every refine dispatch: ~free next to the
    sort/while-loop work, per the FlashSinkhorn IO-bound framing (the
    dispatch is upload/readback-bound anyway).  The actual reduction
    now lives behind the kernel-plane seam in :func:`..ops.refine.
    state_digest` (fused Pallas epilogue when the probe-once gate has
    vouched, the XLA tree otherwise — all-integer, so identical bits
    either way); this name stays as the import surface for the
    coalesce path."""
    from .refine import state_digest

    return state_digest(
        lags_p, choice_p, counts, num_consumers, row_tab=row_tab
    )


def _refine_core(
    lags_p, choice_p, row_tab, counts, totals, limit, P: int,
    num_consumers: int, iters: int, max_pairs, exchange_budget: int,
    bulk: bool = False, delta_k: int = 0,
):
    """Shared tail of every fused refine executable: the resident round
    loop plus the narrowed host-facing output.  Returns
    (narrow choice[P], choice int32[B], row_tab, counts, lags int64[B],
    totals int64[C], rounds int32, exchanges int32, digest int64[5]) —
    everything after the first element stays device-resident with the
    caller; the padded lag vector rides along as the fourth resident
    buffer so the NEXT epoch can scatter-apply a sparse delta instead
    of re-uploading it (:func:`_warm_fused_delta`), and the digest is
    the epoch's fused integrity check (:func:`_state_digest` — the
    readback compares it against host truth, utils/scrub).  ``bulk``
    selects the warm engine's anti-ranked bulk-swap rounds (see
    :func:`..ops.refine.refine_rounds_resident`) with a 4-way partner
    fan per heavy consumer; cold chains keep the parity selection.

    ``delta_k > 0`` appends the O(changed)-readback compaction tail
    (:func:`.delta.compact_changed`) — ``(d_idx int32[K],
    d_vals narrow[K], d_n int32)`` diffing the ENTRY choice against the
    exit choice over ``[:P]`` — so the host can fetch only the changed
    assignments instead of the dense narrow vector.  ``delta_k`` is a
    pure function of ``(exchange_budget, P)`` (:func:`.delta.
    readback_k`), both already compile-time constants here, so the tail
    adds no new executable variants beyond the warmed ladder."""
    # The digest audits the state the epoch STARTED from — the
    # long-lived resident buffers (post-scatter for delta epochs) —
    # not the refine's output: the exchange rounds rewrite the choice
    # entries they move, so a corrupted input row can be silently
    # repaired by the very dispatch that consumed it, and an
    # output-side digest would read clean exactly when detection
    # matters (nondeterministically, by whether the round loop touched
    # the flipped row).  Input-side, any divergence is caught on the
    # FIRST dispatch over the corrupt buffer, deterministically.
    digest = _state_digest(
        lags_p, choice_p, counts, num_consumers, row_tab=row_tab
    )
    entry_choice = choice_p
    choice_p, row_tab, counts, totals, rounds, ex = refine_rounds_resident(
        lags_p, choice_p, row_tab, counts, totals,
        num_consumers=num_consumers, iters=iters, max_pairs=max_pairs,
        exchange_budget=exchange_budget, quality_limit=limit,
        bulk_transfer=bulk, fan=8 if bulk else 1,
    )
    narrow = _narrow_choice(choice_p[:P], num_consumers)
    base = (narrow, choice_p, row_tab, counts, lags_p, totals, rounds,
            ex, digest)
    if delta_k <= 0:
        return base
    d_idx, d_vals, d_n = compact_changed(
        entry_choice, choice_p, narrow, P, delta_k
    )
    return base + (d_idx, d_vals, d_n)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "pack_shift", "iters", "max_pairs", "bucket",
        "interpret", "wide",
    ),
)
def _pallas_cold_chain(
    lags, num_consumers: int, pack_shift: int, iters: int, max_pairs,
    bucket: int, interpret: bool = False, wide: bool = False,
):
    """Cold solve -> table build -> resident refine as ONE dispatch with
    the Pallas round scan (the in-VMEM variant of
    :meth:`StreamingAssignor._cold_solve`'s chained path).  Same contract
    as :func:`_refine_chain` with the greedy solve fused in front; the
    emitted (choice, table, counts) triple seeds the engine's resident
    warm state.  Callers must have passed BOTH Pallas gates host-side."""
    from .batched import _pallas_solve_padded

    P = lags.shape[0]
    B = int(bucket)
    lags_p, valid, choice = _pallas_solve_padded(
        lags, B, num_consumers, pack_shift, wide, interpret=interpret,
    )
    row_tab, counts, totals = build_choice_tables(
        lags_p, valid, choice, num_consumers, table_rows(B, num_consumers)
    )
    return _refine_core(
        lags_p, choice, row_tab, counts, totals, -1.0, P,
        num_consumers, iters, max_pairs, 0,
    )


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs", "bucket")
)
def _refine_chain(
    lags, choice, num_consumers: int, iters: int, max_pairs, bucket: int
):
    """One-dispatch cold-path refine over an exact-shape lag upload.

    ``lags`` is the exact [P] vector (int32 when the host downcast it,
    widened back here); ``choice`` is an exact-shape [P] start (the cold
    chain feeds assign_stream's narrow output without a host round-trip)
    or a padded int32[bucket] buffer.  Padding and the validity mask are
    derived on device from the static shapes, so neither is transferred.
    The per-consumer row table is built in-executable (one padded-size
    sort) and returned device-resident, seeding the fused warm path.

    Returns (narrow choice[P] — the one output the host materializes —
    choice int32[bucket], row_tab, counts, lags int64[bucket], totals,
    rounds, exchanges).
    """
    P = lags.shape[0]
    B = int(bucket)
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, B - P))
    choice_p = _pad_choice(choice, B)
    valid = jnp.arange(B, dtype=jnp.int32) < P
    row_tab, counts, totals = build_choice_tables(
        lags_p, valid, choice_p, num_consumers, table_rows(B, num_consumers)
    )
    return _refine_core(
        lags_p, choice_p, row_tab, counts, totals, -1.0, P,
        num_consumers, iters, max_pairs, 0,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget", "bucket"
    ),
)
def _warm_fused_build(
    lags, choice, limit, num_consumers: int, iters: int, max_pairs,
    exchange_budget: int, bucket: int,
):
    """Fused warm dispatch, table-BUILDING variant: used when the
    resident state is stale (membership repair, host-side edits) — pays
    one padded-size sort to rebuild the [C, M] table, then runs the same
    fused quality-gated refine as the resident variant."""
    P = lags.shape[0]
    B = int(bucket)
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, B - P))
    choice_p = _pad_choice(choice, B)
    valid = jnp.arange(B, dtype=jnp.int32) < P
    row_tab, counts, totals = build_choice_tables(
        lags_p, valid, choice_p, num_consumers, table_rows(B, num_consumers)
    )
    return _refine_core(
        lags_p, choice_p, row_tab, counts, totals, limit, P,
        num_consumers, iters, max_pairs, exchange_budget, bulk=True,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "exchange_budget",
        "delta_k",
    ),
    donate_argnums=(1, 2, 3),
)
def _warm_fused_resident(
    lags, choice, row_tab, counts, limit, num_consumers: int, iters: int,
    max_pairs, exchange_budget: int, delta_k: int = 0,
):
    """THE fused warm-epoch executable: quality evaluation, target test,
    and the full multi-round exchange loop in ONE dispatch over
    device-RESIDENT state.

    Only the exact-shape lag vector crosses host->device; the previous
    choice, the per-consumer row table, and the counts are the donated
    loop-carried buffers from the last dispatch (warm state never
    round-trips to host between rounds, per the FlashSinkhorn fusion
    playbook).  The per-consumer totals under the NEW lags are
    re-derived from the resident table by one gather+sum — the fused
    equivalent of the host-side quality bincount — and the while-loop
    condition tests them against ``limit`` BEFORE the first round, so a
    dispatch whose kept assignment already meets the target performs
    zero rounds.  Returns the same tuple as :func:`_refine_chain`; the
    returned padded lag vector seeds the delta path's resident lag
    buffer.  ``delta_k > 0`` additionally appends the O(changed)
    readback tail (see :func:`_refine_core`)."""
    P = lags.shape[0]
    B = choice.shape[0]
    M = row_tab.shape[1]
    lags_p = jnp.pad(lags.astype(jnp.int64), (0, B - P))
    slot_ok = jnp.arange(M, dtype=jnp.int32)[None, :] < counts[:, None]
    totals = jnp.where(
        slot_ok, lags_p[jnp.clip(row_tab, 0, B - 1)], 0
    ).sum(axis=1)
    return _refine_core(
        lags_p, choice, row_tab, counts, totals, limit, P,
        num_consumers, iters, max_pairs, exchange_budget, bulk=True,
        delta_k=delta_k,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "P", "num_consumers", "iters", "max_pairs", "exchange_budget",
        "delta_k",
    ),
    donate_argnums=(2, 3, 4, 5),
)
def _warm_fused_delta(
    idx, vals, lags_p, choice, row_tab, counts, limit, P: int,
    num_consumers: int, iters: int, max_pairs, exchange_budget: int,
    delta_k: int = 0,
):
    """THE delta-epoch executable: scatter-apply a fixed-size padded
    ``[K]`` (index, value) update to the device-RESIDENT lag buffer,
    then run the exact fused warm-epoch body of
    :func:`_warm_fused_resident` in the same dispatch.

    Only ``idx`` (int32[K]) and ``vals`` (int64[K]) cross host->device —
    O(changed) bytes instead of O(P); the previous choice, row table,
    counts AND the padded lag vector are the donated loop-carried
    buffers from the last dispatch.  Padding entries carry (0, new value
    of index 0): a duplicate scatter of an identical value, so padding
    is a no-op whether or not index 0 is part of the real delta (never
    a conflicting duplicate write, which XLA scatter leaves undefined).
    Bit-parity with the dense path is structural: after the scatter the
    resident buffer holds the identical int64 lag values the dense pad
    would have uploaded, and the refine core is shared."""
    B = choice.shape[0]
    M = row_tab.shape[1]
    lags_p = lags_p.at[idx].set(vals)
    slot_ok = jnp.arange(M, dtype=jnp.int32)[None, :] < counts[:, None]
    totals = jnp.where(
        slot_ok, lags_p[jnp.clip(row_tab, 0, B - 1)], 0
    ).sum(axis=1)
    return _refine_core(
        lags_p, choice, row_tab, counts, totals, limit, P,
        num_consumers, iters, max_pairs, exchange_budget, bulk=True,
        delta_k=delta_k,
    )


class StreamingAssignor:
    """Stateful engine for one topic's periodic rebalance at fixed scale.

    ``imbalance_guardrail`` bounds how far the bounded-churn warm path may
    drift from balance across epochs: after a warm rebalance, if
    ``max_mean_imbalance > guardrail * max(input bound, 1)`` the epoch is
    re-solved cold — greedy plus a refinement pass, so the trip restores
    near-bound quality (unbounded churn for that epoch).  ``None``
    disables the guardrail (pure bounded-churn behavior).
    """

    def __init__(
        self,
        num_consumers: int,
        refine_iters: int = 128,
        imbalance_guardrail: Optional[float] = None,
        # Refinement budget for cold solves (initial epoch, shape change,
        # guardrail trip): churn is unbounded on those paths anyway, and
        # refining makes a guardrail trip actually restore near-bound
        # quality instead of resetting to plain greedy's slack (observed
        # ratio 1.63 unrefined vs ~1.0x refined on a lognormal soak).
        # 0 disables (cold solves return plain greedy).
        cold_refine_iters: int = 64,
        # Warm epochs whose KEPT assignment still scores within this factor
        # of the input-driven bound skip the refine dispatch entirely —
        # zero churn, zero device traffic (see the module docstring).  1.02
        # sits well inside the framework's 1.05 quality target while
        # making steady-drift epochs ~free; None always refines.
        refine_threshold: Optional[float] = 1.02,
        # Opt-in per-epoch jax.profiler StepTraceAnnotation (alongside
        # utils/observability.profile_trace): a Perfetto trace of the
        # warm loop then shows per-epoch step boundaries instead of one
        # undifferentiated blob.  Off by default — the annotation object
        # costs a little even with no profiler attached, and the warm
        # no-op epoch is a ~1.5 ms budget.
        step_trace: bool = False,
        # Optional PER-STREAM flight-recorder ring: every epoch record
        # written to the process-wide aggregate ring (metrics.FLIGHT)
        # is also copied here, so one noisy stream's incident can be
        # dumped without the other tenants' records crowding it out
        # (the sidecar attaches one small ring per live stream and
        # serves it via the stream_flight wire method).
        flight: Optional[metrics.FlightRecorder] = None,
        # Delta epochs (module docstring): when the epoch's changed-lag
        # fraction is at most ``delta_max_fraction`` (and the padded
        # [K] update is strictly fewer bytes than the dense payload),
        # the warm dispatch scatter-applies an (indices, values) delta
        # onto the device-resident lag buffer instead of re-uploading
        # the full [P] vector.  ``delta_buckets`` bounds the pow2 K
        # ladder (DELTA_MIN_K .. DELTA_MIN_K << (buckets - 1)); each
        # rung is one executable — warm them (..warmup) or the first
        # delta epoch per rung pays a compile.  0 buckets or
        # delta_enabled=False keeps every upload dense.
        delta_enabled: bool = True,
        delta_max_fraction: float = 0.125,
        delta_buckets: int = 6,
        # Per-stream ADAPTIVE delta cutoff (ROADMAP delta follow-on
        # (b)): instead of one global ``delta_max_fraction`` knob, the
        # engine tracks this stream's observed changed-fraction
        # distribution (bounded window) and auto-tunes the effective
        # delta/dense cutoff — a steady-2%-churn stream tightens the
        # cutoff so an anomalous wide epoch goes dense instead of
        # exercising a big-K executable, while a steady-20% stream
        # raises it (up to 2x the knob, never past 0.5) so its routine
        # epochs keep the sparse upload.  The strict byte gate (padded
        # delta < dense payload) and the warmed K ladder still bind
        # either way.  False pins the cutoff to the global knob.
        delta_adaptive: bool = True,
        # Multi-device backend selection for COLD solves (sharded/):
        # "auto" (default) follows the process-wide active mesh
        # manager via ops/dispatch; an explicit
        # :class:`..sharded.mesh.MeshManager` pins this engine to it;
        # None pins the engine single-device regardless of any global
        # manager (a mesh-off service's engines must not adopt a
        # co-resident instance's mesh).
        mesh_backend="auto",
    ):
        self.num_consumers = int(num_consumers)
        self.refine_iters = int(refine_iters)
        self.cold_refine_iters = int(cold_refine_iters)
        if imbalance_guardrail is not None and imbalance_guardrail < 1.0:
            raise ValueError(
                f"imbalance_guardrail={imbalance_guardrail} must be >= 1.0"
            )
        if refine_threshold is not None and refine_threshold < 1.0:
            raise ValueError(
                f"refine_threshold={refine_threshold} must be >= 1.0"
            )
        self.imbalance_guardrail = imbalance_guardrail
        self.refine_threshold = refine_threshold
        self.step_trace = bool(step_trace)
        self.flight = flight
        if not 0.0 < float(delta_max_fraction) <= 1.0:
            raise ValueError(
                f"delta_max_fraction={delta_max_fraction} must be in "
                "(0, 1]"
            )
        if int(delta_buckets) < 0:
            raise ValueError(
                f"delta_buckets={delta_buckets} must be >= 0"
            )
        self.delta_enabled = bool(delta_enabled) and int(delta_buckets) > 0
        self.delta_max_fraction = float(delta_max_fraction)
        self.delta_buckets = int(delta_buckets)
        self.delta_adaptive = bool(delta_adaptive)
        self.mesh_backend = mesh_backend
        # Observed changed-fraction window (bounded: deque maxlen) and
        # the last effective cutoff actually applied — the stats /
        # dump_metrics surface of the adaptive knob.
        from collections import deque

        self._churn_fractions = deque(maxlen=_ADAPT_WINDOW)
        self.last_effective_delta_fraction = self.delta_max_fraction
        self._m_eff_fraction = metrics.REGISTRY.gauge(
            "klba_delta_effective_fraction"
        )
        # Top rung of the K ladder; a delta whose bucket exceeds it
        # falls back to the dense upload.
        ladder = delta_k_ladder(self.delta_buckets)
        self._delta_kmax = ladder[-1] if self.delta_enabled else 0
        # Set transiently by submit_epoch: when non-None, the resident
        # warm dispatch routes through the megabatch coalescer
        # (ops/coalesce) instead of dispatching inline.
        self._coalescer = None
        # Transient SLO placement for the coalesced submission
        # (class name, rank, absolute deadline) — see submit_epoch.
        self._slo_submit = ("standard", 1, None)
        self._epoch_num = 0
        # Pre-bound registry series (utils/metrics): the warm no-op epoch
        # is the hot path (<1% overhead budget, asserted in tests), so
        # the per-epoch records must be plain pre-resolved observes, not
        # name lookups.
        self._m_churn = metrics.REGISTRY.histogram("klba_stream_churn")
        self._m_quality_milli = metrics.REGISTRY.histogram(
            "klba_stream_quality_ratio_milli"
        )
        self._m_quality_last = metrics.REGISTRY.gauge(
            "klba_stream_quality_ratio"
        )
        self._m_guardrail = metrics.REGISTRY.counter(
            "klba_stream_guardrail_trips_total"
        )
        # H2D accounting + delta-epoch outcomes (pre-bound: these sit
        # on the warm dispatch path).  The byte counters charge only
        # the WARM paths' lag payloads — the designated upload sites
        # lint rule L016 funnels future code through.
        self._m_h2d_dense = metrics.REGISTRY.counter(
            "klba_h2d_bytes_total", {"path": "dense"}
        )
        self._m_h2d_delta = metrics.REGISTRY.counter(
            "klba_h2d_bytes_total", {"path": "delta"}
        )
        self._m_delta = {
            o: metrics.REGISTRY.counter(
                "klba_delta_epochs_total", {"outcome": o}
            )
            for o in ("applied", "fallback", "resync")
        }
        # D2H accounting — the readback mirror of the H2D pair above:
        # the dense narrow fetch vs the O(changed) compaction tail
        # (ops/delta), plus per-epoch outcomes mirroring the upload
        # ladder's counter so both directions of the delta plane read
        # the same way in dump_metrics.
        self._m_d2h_dense = metrics.REGISTRY.counter(
            "klba_d2h_bytes_total", {"path": "dense"}
        )
        self._m_d2h_delta = metrics.REGISTRY.counter(
            "klba_d2h_bytes_total", {"path": "delta"}
        )
        self._m_rb = {
            o: metrics.REGISTRY.counter(
                "klba_rb_delta_epochs_total", {"outcome": o}
            )
            for o in ("applied", "fallback", "overflow")
        }
        # True when the LAST cold solve was served by the P-sharded
        # backend (stats surface; reset per cold solve).
        self._cold_was_sharded = False
        self._prev_choice: Optional[np.ndarray] = None
        # Device-RESIDENT warm state between dispatches: (padded int32
        # choice[bucket], per-consumer row table int32[C, M], counts
        # int32[C], padded int64 lags[bucket]).  The fused warm
        # executable takes these as DONATED buffers and returns their
        # successors, so the engine's own state never round-trips to
        # host.  While this stream's roster is locked in the megabatch
        # coalescer the value is a ResidentRow HANDLE instead
        # (ops/coalesce): the buffers live stacked in the
        # coalescer-owned batch and the handle names this stream's row.
        # None = stale (host-side edits: repair, remap, reset, shape
        # change).
        self._resident = None
        # True while the resident buffers are P-sharded over the mesh
        # (sharded/resident placement): the warm refine dispatch then
        # launches a multi-participant collective program and must hold
        # the mesh dispatch gate (sharded/mesh) — concurrent collective
        # launches starve the runtime's rendezvous.
        self._resident_sharded = False
        # Host mirror of the resident lag buffer's first P entries —
        # the base the delta differ diffs against.  None whenever the
        # resident state is stale (the mirror lives and dies with it).
        self._lag_mirror: Optional[np.ndarray] = None
        # Quarantine state (utils/scrub): the buffer classes the last
        # failed integrity check named, None while healthy.  Armed by
        # :meth:`quarantine_resident`; cleared (and counted as a heal)
        # when the next dispatch rebuilds the resident state from host
        # truth and adopts fresh successors.
        self._quarantined: Optional[list] = None
        self.last_stats = StreamingStats()

    def rebalance(self, lags: np.ndarray) -> np.ndarray:
        """Produce choice int32[P] for the current lag vector."""
        faults.fire("stream.refine")  # fault point: poisoned warm stream
        self._epoch_num += 1
        with metrics.span("stream.epoch"):
            if self.step_trace:
                with jax.profiler.StepTraceAnnotation(
                    "klba_stream_epoch", step_num=self._epoch_num
                ):
                    choice = self._rebalance_inner(lags)
            else:
                choice = self._rebalance_inner(lags)
        s = self.last_stats
        ratio = s.quality_ratio
        self._m_churn.observe(s.churn)
        self._m_quality_milli.observe(int(ratio * 1000))
        self._m_quality_last.set(ratio)
        rec = {
            "epoch": self._epoch_num,
            "P": int(lags.shape[0]),
            "C": self.num_consumers,
            "cold_start": s.cold_start,
            "refined": s.refined,
            "guardrail_tripped": s.guardrail_tripped,
            "churn": s.churn,
            "repaired_rows": s.repaired_rows,
            "quality_ratio": ratio,
            "max_mean_imbalance": s.max_mean_imbalance,
            "imbalance_bound": s.imbalance_bound,
            "count_spread": s.count_spread,
            "refine_rounds": s.refine_rounds,
            "refine_exchanges": s.refine_exchanges,
            "delta_effective_fraction": s.delta_effective_fraction,
            "sharded_solve": s.sharded_solve,
        }
        if self.flight is not None:
            # A recorder takes ownership of its record (annotates it in
            # place), so the per-stream ring gets its own shallow copy.
            self.flight.record("stream_epoch", dict(rec))
        metrics.FLIGHT.record("stream_epoch", rec)
        if s.guardrail_tripped:
            self._m_guardrail.inc()
            trace_mod.mark("guardrail")
            metrics.FLIGHT.auto_dump(
                "guardrail", {"epoch": self._epoch_num,
                              "quality_ratio": ratio}
            )
        return choice

    def submit_epoch(
        self,
        lags: np.ndarray,
        coalescer,
        slo_class: str = "standard",
        rank: int = 1,
        deadline_at: Optional[float] = None,
    ) -> np.ndarray:
        """One rebalance epoch whose fused warm dispatch — if the epoch
        needs one — is routed through ``coalescer``
        (:class:`..ops.coalesce.MegabatchCoalescer`): instead of
        dispatching inline, the epoch parks on a future and the
        coalescer megabatches it with every concurrent stream's epoch
        in the same shape bucket into ONE vmapped resident dispatch.

        Everything else about the epoch is :meth:`rebalance` verbatim —
        the host-side quality gate still skips still-balanced epochs
        with zero device traffic, cold solves and stale-resident
        (table-build) dispatches stay inline (they are rare,
        shape-changing events a megabatch cannot absorb), and a flush
        failure surfaces on THIS stream only (the coalescer isolates
        rows; see ops/coalesce).  Intended caller: the sidecar's
        stream_assign path when more than one stream is live; a lone
        tenant keeps the inline :meth:`rebalance` fast path.

        ``slo_class`` / ``rank`` / ``deadline_at`` are the submission's
        SLO placement (utils/overload): rank orders the flush so
        deadline-critical streams never park behind a full lower-class
        wave, and ``deadline_at`` (absolute, in the coalescer's —
        registry — clock) lets the flush re-route or shed a row whose
        class budget cannot survive a full wave."""
        self._coalescer = coalescer
        self._slo_submit = (str(slo_class), int(rank), deadline_at)
        try:
            return self.rebalance(lags)
        finally:
            self._coalescer = None
            self._slo_submit = ("standard", 1, None)

    def _rebalance_inner(self, lags: np.ndarray) -> np.ndarray:
        ensure_x64()  # int64 lags would silently downcast to int32 otherwise
        lags = np.ascontiguousarray(lags, dtype=np.int64)
        if lags.size and int(lags.min()) < 0:
            # Non-negative lags are a documented precondition of every
            # kernel downstream (packed sort keys, the int32 upload
            # downcast) AND of the exact_bincount guard below — with mixed
            # signs, cancellation can keep the f64 total small while
            # per-consumer partial sums exceed 2^53, making the fast
            # weighted bincount silently inexact.  The reference's lag
            # formula clamps at 0, so a negative lag here is a caller bug.
            raise ValueError("lags must be non-negative")
        P = lags.shape[0]
        stats = StreamingStats()
        # The delta/dense cutoff in force THIS epoch: decided from the
        # window of PAST observed fractions (this epoch's own fraction
        # is recorded after the diff, so the cutoff never chases the
        # sample it is gating).
        self.last_effective_delta_fraction = (
            self._effective_delta_fraction()
        )
        stats.delta_effective_fraction = (
            self.last_effective_delta_fraction
        )
        self._m_eff_fraction.set(self.last_effective_delta_fraction)

        # Input-driven quantities that cannot change within one rebalance:
        # computed once, shared by every quality evaluation below.
        bound = count_constrained_bound(lags, self.num_consumers)
        # f64 sum for the guard: an int64 sum could wrap past 2^63 and
        # spuriously select the inexact path in exactly the regime where
        # the exact fallback matters (f64 cannot wrap, only round — fine
        # for a > / < threshold check at the 2^53 boundary).
        exact_bincount = float(lags.sum(dtype=np.float64)) < float(1 << 53)

        prev = self._prev_choice
        if prev is None or prev.shape[0] != P:
            stats.cold_start = True
            choice = self._cold_solve(lags)
            stats.sharded_solve = self._cold_was_sharded
            prev_for_churn = None
            self._fill_quality_stats(stats, choice, lags, bound,
                                     exact_bincount)
        else:
            # Membership repair: after remap_members the previous choice
            # may hold orphaned rows (-1, owner left) or counts above the
            # new ceiling (group shrank/grew).  Re-seat ONLY the moving
            # rows host-side.  Repair is not an exchange — orphaned rows
            # must be owned regardless of the refine budget (the churn
            # bound reads repaired_rows + 2 * refine_iters).
            prev_for_churn = prev  # churn counts repair moves too
            choice, stats.repaired_rows = self._repair_choice(prev, lags)
            if stats.repaired_rows:
                self._drop_resident()  # device state is stale now

            # Evaluate the KEPT assignment under the new lags (host-side,
            # one weighted bincount) and dispatch the refinement only when
            # it is actually needed: a still-balanced epoch is a no-op —
            # zero churn, zero device traffic.  (The fused executable
            # re-evaluates on device and early-exits at the same target,
            # so the host gate only decides WHETHER to dispatch at all.)
            self._fill_quality_stats(stats, choice, lags, bound,
                                     exact_bincount)
            needs_refine = self.refine_iters > 0 and (
                self.refine_threshold is None
                or stats.max_mean_imbalance
                > self.refine_threshold * max(stats.imbalance_bound, 1.0)
            )
            if needs_refine:
                choice = self._dispatch_warm_refine(lags, choice, stats)
                stats.refined = True

        # Quality guardrail: a warm epoch whose imbalance drifted past the
        # allowance re-solves cold (the churn bound intentionally yields).
        # If the threshold skipped the bounded refine this epoch (possible
        # when the guardrail is tighter than refine_threshold), try the
        # cheap bounded-churn refine FIRST — only an epoch the refine
        # cannot rescue pays the unbounded cold re-solve.
        if self.imbalance_guardrail is not None and not stats.cold_start:
            allowance = self.imbalance_guardrail * max(
                stats.imbalance_bound, 1.0
            )
            if (
                stats.max_mean_imbalance > allowance
                and not stats.refined
                and self.refine_iters > 0
            ):
                choice = self._dispatch_warm_refine(lags, choice, stats)
                stats.refined = True
            if stats.max_mean_imbalance > allowance:
                stats.guardrail_tripped = True
                stats.cold_start = True
                choice = self._cold_solve(lags)
                stats.sharded_solve = self._cold_was_sharded
                self._fill_quality_stats(stats, choice, lags, bound,
                                         exact_bincount)

        if prev_for_churn is not None:
            stats.churn = int((choice != prev_for_churn).sum())
        self._prev_choice = choice
        self.last_stats = stats
        return choice

    def _bucket(self, P: int) -> int:
        """Padded refine shape: pow2 bucket on accelerators (sort-network
        friendly), the finer 4096-chunk on CPU where a pow2 pad wastes up
        to ~2x sort work — either way the jit cache stays bounded across
        slowly-varying P."""
        return pad_chunk(P) if jax.default_backend() == "cpu" else pad_bucket(P)

    def _drop_resident(self) -> None:
        """Invalidate the device-resident warm state AND its host lag
        mirror together — a mirror that outlives the buffer it mirrors
        would let a later delta scatter onto the wrong base."""
        self._resident = None
        self._resident_sharded = False
        self._lag_mirror = None

    def _adopt_resident(self, resident, lags: np.ndarray) -> None:
        """Install a dispatch's resident successors and mirror the lag
        vector they were computed under (copied: the caller's array may
        be mutated between epochs).  A quarantined engine reaching this
        point has HEALED: the successors were rebuilt from host truth
        (the digest on the way in verified them), counted per buffer.
        The ``device.corrupt.*`` chaos points fire here — the readback
        boundary — so drills can silently flip bits in the freshly
        adopted buffers (host mirror left intact) and exercise the
        whole detect/quarantine/heal plane."""
        if self._quarantined is not None:
            scrub_mod.record_quarantine(
                self._quarantined, "healed", source="rebuild"
            )
            self._quarantined = None
        resident = self._corrupt_resident(resident, lags.shape[0])
        resident = self._place_resident(resident, lags.shape[0])
        self._resident = resident
        self._lag_mirror = np.array(lags, dtype=np.int64, copy=True)

    def _collective_gate(self):
        """The mesh dispatch gate when the resident buffers are
        P-sharded (their fused programs are collective-bearing and
        concurrent collective launches starve the runtime's
        rendezvous — sharded/mesh), a no-op context otherwise.  Taken
        around the LAUNCH only, never around a coalescer park (a
        parked thread holding the gate would serialize wave
        formation into single-row flushes)."""
        if self._resident_sharded:
            from ..sharded.mesh import dispatch_gate

            return dispatch_gate()
        return contextlib.nullcontext()

    def _resident_mesh_manager(self, num_rows: int):
        """The mesh manager electing this stream's resident P-shard
        placement — the same selection rule as the sharded cold solve
        (``mesh_backend`` pin/auto + the solve_min_rows floor), so the
        resident state shards exactly when the cold path does."""
        mb = self.mesh_backend
        if mb is None:
            return None
        if mb == "auto":
            from .dispatch import sharded_solve_manager

            return sharded_solve_manager(num_rows, self.num_consumers)
        return mb if (
            mb.active
            and self.num_consumers >= 2
            and mb.should_shard_solve(num_rows)
        ) else None

    def _place_resident(self, resident, P: int):
        """Opt-in P-sharded placement of the four resident buffers
        (sharded/resident): when the active mesh manager elects the P
        backend for this shape, the [B] row buffers shard over the
        tenant's "p" slice and the consumer-axis state replicates —
        values (and therefore the digest/quarantine/seed_choice
        contracts) are bit-identical, only bytes move.  Locked-roster
        handles are skipped (the coalescer owns that placement); any
        failure keeps the single-device buffers and degrades the
        manager so the fleet falls back with it."""
        self._resident_sharded = False
        if getattr(resident, "materialize", None) is not None:
            return resident
        mgr = self._resident_mesh_manager(P)
        if mgr is None:
            return resident
        from ..sharded import resident as resident_mod

        try:
            mesh = mgr.solve_mesh()
            if not resident_mod.shardable_rows(
                mesh, int(resident[0].shape[0])
            ):
                return resident
            placed = resident_mod.place_resident(mesh, resident)
        except Exception:
            LOGGER.warning(
                "resident P-shard placement failed; keeping the "
                "single-device buffers", exc_info=True,
            )
            mgr.degrade("resident")
            return resident
        metrics.REGISTRY.counter(
            "klba_resident_placed_total", {"axis": "p"}
        ).inc()
        self._resident_sharded = True
        return placed

    def _corrupt_resident(self, resident, P: int):
        """Chaos injection site (fault points ``device.corrupt.choice``
        / ``.counts`` / ``.lags``): when a drill's plan fires, one
        seeded bit of the named freshly-adopted device buffer is
        flipped — the host mirror is deliberately NOT updated, so the
        device state silently diverges exactly like a real memory
        fault.  Zero-cost off (one global load); locked-roster handles
        are skipped (the coalescer owns that injection site)."""
        if faults.active() is None or getattr(
            resident, "materialize", None
        ) is not None:
            return resident
        plan = scrub_mod.corruption_plan(limit=P)
        if not plan:
            return resident
        slot = {"choice": 0, "row_tab": 1, "counts": 2, "lags": 3}
        bufs = list(resident)
        for buffer, seed in plan:
            i = slot[buffer]
            host = scrub_mod.flip_bit(
                np.asarray(bufs[i]), seed,
                # counts and the [C, M] row table are audited over
                # their FULL extent (every table slot carries either a
                # row index or the sentinel), so no prefix bound.
                limit=None if buffer in ("counts", "row_tab") else P,
            )
            # noqa-justification: this re-upload is injected corruption
            # (drill machinery), not a counted lag payload — the H2D
            # byte series must not see it.
            bufs[i] = jax.device_put(host)  # noqa: L016
            LOGGER.warning(
                "injected device.corrupt.%s bit flip (seed %d)",
                buffer, seed,
            )
        return tuple(bufs)

    def quarantine_resident(
        self, buffers, source: str = "scrub", record: bool = True
    ) -> None:
        """Quarantine the device-resident warm state: an integrity
        check (per-epoch digest, scrubber audit, or a megabatch row
        check) found it diverged from host truth.  The resident
        buffers and the lag mirror are dropped TOGETHER; the host
        previous-choice vector stays — it is the truth the next
        dispatch rebuilds from, bit-exact by the same contract
        :meth:`seed_choice` recovery replays — and the heal is counted
        when that rebuild's successors are adopted.  ``record=False``
        skips the quarantine/heal accounting entirely (the warm-up's
        heal-path replay must not make every boot look like a real
        corruption event in ``klba_quarantine_total``)."""
        self._quarantined = list(buffers) if record else None
        self._drop_resident()
        if record:
            scrub_mod.record_quarantine(
                buffers, "quarantined", source=source
            )

    @property
    def quarantined(self) -> bool:
        """True between a failed integrity check and the healing
        rebuild (the sidecar's stats surface reads this)."""
        return self._quarantined is not None

    def _verify_digest(
        self, digest, P: int, lag_sum: Optional[int], source: str
    ) -> None:
        """Compare a dispatch's fused device digest against host truth
        (utils/scrub.digest_failures).  A mismatch quarantines this
        engine (the corrupt successors are never adopted) and raises
        :class:`..utils.scrub.CorruptStateDetected` — a
        ``SolveRejected`` subtype, so the service serves the request
        through the degraded ladder (kept_previous / host snake) and
        no breaker is charged; repeated failures escalate there."""
        fails = scrub_mod.digest_failures(digest, P, lag_sum)
        if not fails:
            return
        LOGGER.warning(
            "resident-state digest FAILED (%s) on the %s path; "
            "quarantining", ",".join(fails), source,
        )
        self.quarantine_resident(fails, source=source)
        raise scrub_mod.CorruptStateDetected(
            f"resident-state digest mismatch ({','.join(fails)}) on "
            f"the {source} path; stream quarantined — serving falls "
            "back to host truth and the state heals on the next epoch",
            fails,
        )

    def _cold_solve(self, lags: np.ndarray) -> np.ndarray:
        """Fresh greedy solve + quality refinement (unbounded-churn path;
        budget = ``cold_refine_iters``, 0 disables).  When the mesh
        manager elects the P-axis-sharded backend for this shape
        (:meth:`_sharded_cold_solve`), ONE sharded dispatch serves the
        cold solve instead — single-device remains the default and the
        degradation target.

        The refined path runs solve -> refine as one chained async
        dispatch with a single device->host readback at the end — on a
        high-latency transport a host round-trip between the two would
        double the cold cost.  The lag payload is uploaded once and shared
        by both kernels."""
        self._cold_was_sharded = False
        with metrics.span("stream.cold_solve"):
            return self._cold_solve_inner(lags)

    def _sharded_cold_solve(self, lags: np.ndarray):
        """The P-axis-sharded cold backend (ops/dispatch backend
        selection): when the active mesh manager elects to shard this
        shape, ONE sharded seed+refine dispatch replaces the
        single-device greedy chain; the device-resident warm state is
        left stale and rebuilt by the next warm epoch from this choice
        — exactly the :meth:`seed_choice` contract, so the warm loop
        (and the megabatch) stay on their single/stream-sharded paths.
        Returns None when the single-device backend should serve
        (unconfigured/degraded mesh, shape below the floor, or a
        sharded dispatch failing — which also degrades the manager so
        the fleet falls back, not just this request)."""
        mb = self.mesh_backend
        if mb is None:
            return None  # pinned single-device
        if mb == "auto":
            from .dispatch import sharded_solve_manager

            mgr = sharded_solve_manager(
                lags.shape[0], self.num_consumers
            )
        else:
            mgr = mb if (
                mb.active
                and self.num_consumers >= 2
                and mb.should_shard_solve(lags.shape[0])
            ) else None
        if mgr is None:
            return None
        # Quality-mode selection for the sharded cold solve
        # (ops/dispatch, ``tpu.assignor.quality.mode``): this hook
        # holds an electing mesh, so it is the one caller that can
        # actually SHARD the linear duals — under "auto" (and a
        # pinned "linear") the cold solve runs the mirror-prox duals
        # P-sharded over the same mesh
        # (sharded/solve.solve_linear_sharded) instead of the
        # seed+exchange program; only a pinned "sinkhorn" keeps the
        # exchange program.  Both fall back down the identical
        # single-device ladder.
        from .dispatch import quality_mode

        use_linear = quality_mode() != "sinkhorn"
        from ..sharded.solve import solve_linear_sharded, solve_sharded

        try:
            with metrics.span("stream.sharded_solve"):
                if use_linear:
                    choice, _, _, _ = solve_linear_sharded(
                        mgr.solve_mesh(), lags, self.num_consumers,
                        refine_iters=self.cold_refine_iters,
                    )
                else:
                    choice, _, _, _ = solve_sharded(
                        mgr.solve_mesh(), lags, self.num_consumers,
                        refine_iters=self.cold_refine_iters,
                    )
        except Exception:
            LOGGER.warning(
                "sharded cold solve failed; degrading to the "
                "single-device backend", exc_info=True,
            )
            mgr.degrade("solve")
            return None
        self._cold_was_sharded = True
        self._drop_resident()
        return np.asarray(choice).astype(np.int32)

    def _linear_cold_solve(self, lags: np.ndarray):
        """Single-device linear-OT quality cold solve (ops/linear_ot):
        selected only when ``tpu.assignor.quality.mode`` is PINNED to
        "linear" — under "auto" the single-device greedy+refine cold
        chain keeps its measured latency contract and the linear mode
        engages through the sharded hook above.  Serves the choice as
        a cold seed exactly like the sharded backend (resident state
        dropped, rebuilt by the next warm epoch); any failure falls
        open to the greedy chain.  Returns None when not selected."""
        from .dispatch import quality_mode

        if quality_mode() != "linear" or self.num_consumers < 2:
            return None
        from .linear_ot import assign_topic_linear
        from .packing import pad_topic_rows

        try:
            with metrics.span("stream.linear_solve"):
                # Pad to the pow2 bucket BEFORE the solve: the linear
                # executables key on the padded shape, so drifting
                # partition counts reuse one warmed compile per bucket
                # (exactly what the per-mode warm-up drove) instead of
                # tracing per exact P on the serve path.
                lags_p, pids_p, valid_p = pad_topic_rows(lags)
                choice, _, _ = assign_topic_linear(
                    lags_p, pids_p, valid_p,
                    num_consumers=self.num_consumers,
                    refine_iters=self.cold_refine_iters,
                )
                choice = np.asarray(choice)[: lags.shape[0]]
        except Exception:
            LOGGER.warning(
                "linear-OT cold solve failed; serving this epoch "
                "through the greedy cold chain", exc_info=True,
            )
            return None
        self._drop_resident()
        return np.asarray(choice).astype(np.int32)

    def _cold_solve_inner(self, lags: np.ndarray) -> np.ndarray:
        C = self.num_consumers
        sharded = self._sharded_cold_solve(lags)
        if sharded is not None:
            return sharded
        linear = self._linear_cold_solve(lags)
        if linear is not None:
            return linear
        if self.cold_refine_iters <= 0 or C < 2:
            self._drop_resident()
            return np.asarray(
                assign_stream(lags, num_consumers=C)
            ).astype(np.int32)
        P = lags.shape[0]
        if jax.default_backend() == "cpu":
            # Host-presort fast path (see assign_stream); device_put is
            # free on CPU so there is no shared-upload concern.
            choice0 = assign_stream(lags, num_consumers=C)
            payload = lags
        else:
            from .batched import totals_rank_bits_for

            payload, shift = stream_payload(lags)
            rb = totals_rank_bits_for(payload, C)
            # Pallas in-VMEM solve + refine in one dispatch when both
            # gates pass (same condition set as assign_stream; the
            # probe-once gate never probes here — warm-up/bench resolve
            # it off the rebalance path).
            from .rounds_pallas import (
                pallas_mode_for,
                rounds_pallas_available,
            )

            mode = pallas_mode_for(lags, C, -(-P // C))
            if mode and rounds_pallas_available(mode=mode):
                observe_pack_shift(
                    ("cold_pallas", lags.shape, C), (shift, mode)
                )
                narrow, *resident = _pallas_cold_chain(
                    payload, num_consumers=C, pack_shift=shift,
                    iters=self.cold_refine_iters, max_pairs=None,
                    bucket=self._bucket(P), wide=(mode == "wide"),
                )
                with metrics.device_phase("refine"):
                    narrow_np, digest_np = jax.device_get(
                        (narrow, resident[7])
                    )
                self._verify_digest(
                    digest_np, P, int(lags.sum(dtype=np.int64)),
                    source="cold",
                )
                self._adopt_resident(tuple(resident[:4]), lags)
                return narrow_np.astype(np.int32)
            observe_pack_shift(("stream", lags.shape, C), (shift, rb))
            with metrics.span("stream.h2d"):
                # ONE upload, shared by both kernels.  The device phase
                # rides inside the span (same pairing as linear_ot's
                # h2d) so the epoch trace separates transfer dispatch
                # from compute even on the cold chain.
                with metrics.device_phase("h2d"):
                    payload = jax.device_put(payload)
            choice0 = _stream_device(
                payload, num_consumers=C, pack_shift=shift,
                totals_rank_bits=rb,
            )
        narrow, *resident = _refine_chain(
            payload, choice0, num_consumers=C,
            iters=self.cold_refine_iters, max_pairs=None,
            bucket=self._bucket(P),
        )
        with metrics.device_phase("refine"):
            narrow_np, digest_np = jax.device_get((narrow, resident[7]))
        self._verify_digest(
            digest_np, P, int(lags.sum(dtype=np.int64)), source="cold"
        )
        self._adopt_resident(tuple(resident[:4]), lags)
        return narrow_np.astype(np.int32)

    def _quality_limit(self, bound: float, total_lag: float) -> float:
        """Device-side early-exit target for the fused refine: peak
        consumer total at the TIGHTER of refine_threshold / guardrail
        (the same count-constrained normalization the host gate uses).
        Negative disables (refine until budget/patience)."""
        ratios = [
            r for r in (self.refine_threshold, self.imbalance_guardrail)
            if r is not None
        ]
        if not ratios:
            return -1.0
        mean_load = total_lag / max(self.num_consumers, 1)
        return min(ratios) * max(bound, 1.0) * mean_load

    def _dispatch_warm_refine(
        self, lags: np.ndarray, choice: np.ndarray, stats: StreamingStats
    ) -> np.ndarray:
        """ONE fused device dispatch for the whole warm epoch's quality
        work: re-evaluate the kept assignment's totals under the new lags
        (device-side, from the resident table), test them against the
        quality target, and run the multi-round exchange loop with its
        three early exits (target met / peak stagnant for ``patience``
        rounds / exchange budget spent).  ``refine_iters`` is accounted
        as APPLIED exchanges — churn stays bounded by 2 * refine_iters —
        so a concentrated-drift epoch can spend its whole budget on one
        stubborn peak across many cheap rounds instead of charging
        rounds x pairs up front (the r5 regression: 23 charged rounds
        exhausted a 512 budget at quality 1.12).

        Transfer contract: exact-shape lags up (int32 when the range
        allows), narrow choice back; the previous choice, row table, and
        counts live device-resident between dispatches as DONATED
        buffers (zero re-upload of engine state).  Fills ``stats`` from
        the executable's own totals/counts outputs — the fused
        replacement for the post-refine host bincount."""
        with metrics.span("stream.refine"):
            if self._resident_sharded:
                # P-sharded resident: the fused refine is a collective
                # program, so this is a sharded dispatch boundary like
                # the cold solve's — probe the collective health
                # (``mesh.collective`` fault point) BEFORE launching;
                # the inline launch itself takes the mesh dispatch
                # gate at its call sites (``_collective_gate``).
                # On a lost collective (or a manager that degraded
                # under another stream's feet) the resident drops and
                # the epoch re-solves on the CURRENT rung's placement —
                # always a valid assignment, one rung down.
                from ..sharded.mesh import MeshCollectiveError

                mgr = self._resident_mesh_manager(lags.shape[0])
                if mgr is None:
                    self._drop_resident()
                    stats.cold_start = True
                    out = self._cold_solve(lags)
                    stats.sharded_solve = self._cold_was_sharded
                    return out
                try:
                    mgr.check_collective()
                except MeshCollectiveError:
                    LOGGER.warning(
                        "mesh collective lost at the warm-refine "
                        "boundary; re-solving this epoch on the "
                        "degraded placement"
                    )
                    self._drop_resident()
                    stats.cold_start = True
                    out = self._cold_solve(lags)
                    stats.sharded_solve = self._cold_was_sharded
                    return out
            return self._dispatch_warm_refine_inner(lags, choice, stats)

    def _dispatch_warm_refine_inner(
        self, lags: np.ndarray, choice: np.ndarray, stats: StreamingStats
    ) -> np.ndarray:
        C = self.num_consumers
        P = lags.shape[0]
        B = self._bucket(P)
        budget = self.refine_iters
        # Bulk rounds: 16 pairs = the top 2 over-target consumers, each
        # fanned across 8 light partners per round (the [K, M] slice
        # work stays tiny while a stubborn peak drains 8 partners' worth
        # of swaps per round); the pair-major (heaviest-first) budget
        # quota still spends churn on the worst offenders first.  The
        # old ~sqrt(budget) split existed for one-exchange-per-pair
        # rounds, where width traded against rotation depth.
        pairs = min(self.num_consumers // 2, 16)
        limit = self._quality_limit(
            stats.imbalance_bound, float(lags.sum(dtype=np.float64))
        )
        # Host truth for the epoch's fused integrity digest (and the
        # delta paths' conservation check): the int64 lag sum,
        # wrap-consistent with the device reductions.
        lag_sum = int(lags.sum(dtype=np.int64))
        # O(changed) READBACK width (ops/delta): a pure function of
        # (exchange_budget, P) — both already compile keys of the warm
        # executables — so threading it through creates no variants
        # beyond the warmed ladder.  Gated on delta_enabled: the warmup
        # stream job pins delta_enabled=False, so the dense-readback
        # executables it warms stay byte-identical, while the delta job
        # warms the tailed variants at every K rung.  ``rb_base`` is the
        # host dense view the compaction tail diffs against — valid on
        # the resident path because every host-side choice edit drops
        # the resident state (repair/remap/seed/reset), so the entry
        # choice on device always equals ``choice`` here.
        rb_k = readback_k(budget, P) if self.delta_enabled else 0
        rb_base = choice
        payload, _ = stream_payload(lags)
        resident = self._resident
        # The resident state is either the engine's own (choice, row_tab,
        # counts, lags) device tuple or — while this stream's roster is
        # locked in the megabatch coalescer — a ResidentRow handle whose
        # buffers live stacked in the coalescer-owned batch (ops/coalesce).
        handle_matches = getattr(resident, "matches", None)
        if resident is not None and (
            handle_matches(B, C, table_rows(B, C))
            if handle_matches is not None
            else (
                resident[0].shape[0] == B
                and resident[1].shape == (C, table_rows(B, C))
            )
        ):
            # A lag-range drift across the int32 boundary changes the
            # payload dtype and retraces the fused executable — log it
            # like every other recompile-on-drift path (the "shift" here
            # is the upload width).
            observe_pack_shift(
                ("warm_fused", lags.shape, C),
                int(payload.dtype.itemsize) * 8,
            )
            delta = self._delta_plan(lags, payload)
            if self._coalescer is not None:
                # Megabatched epoch (submit_epoch): park on the
                # coalescer's future — the flush stacks this epoch with
                # its same-bucket batchmates into ONE vmapped fused
                # dispatch, and the resident successors come back as
                # rows of the batch output (still device-resident).
                # The delta plan rides along: a locked wave whose every
                # row carries one applies the stacked [N, K] delta path
                # (O(N·changed) upload) instead of staging [N, B].
                from .coalesce import DeadlineReroute, EpochSubmission

                klass, rank, deadline_at = self._slo_submit
                try:
                    r = self._coalescer.submit(
                        EpochSubmission(
                            payload=payload, bucket=B, resident=resident,
                            limit=limit, num_consumers=C, iters=budget,
                            max_pairs=pairs, exchange_budget=budget,
                            scope=metrics.capture_scope(),
                            owner=self,
                            abandoned=capture_abandon_check(),
                            klass=klass, rank=rank,
                            deadline_at=deadline_at,
                            delta_idx=(
                                delta[0][: delta[3]]
                                if delta is not None else None
                            ),
                            delta_vals=(
                                delta[1][: delta[3]]
                                if delta is not None else None
                            ),
                            lag_sum=lag_sum,
                        )
                    ).result()
                except DeadlineReroute:
                    # Deadline triage re-routed this row out of the
                    # wave: the remaining class budget cannot survive a
                    # full flush, so THIS (already-parked) thread runs
                    # the inline dispatch below — in parallel with any
                    # other rerouted laggards, leaving the flusher
                    # admission-only.
                    pass
                except scrub_mod.CorruptStateDetected as exc:
                    # The wave's readback digest-checked THIS stream's
                    # row and found it diverged (utils/scrub): the
                    # coalescer already evicted the roster (one
                    # invalidation, one re-stack, re-lock); quarantine
                    # the engine side too — the handle points into the
                    # frozen corrupt batch and must never be reused —
                    # and let the rejection reach the service's
                    # degraded ladder (kept_previous / snake).
                    self.quarantine_resident(exc.buffers, source="wave")
                    raise
                else:
                    self._adopt_resident(r.resident, lags)
                    self._fill_stats_from_device(
                        stats, r.totals, r.counts, r.rounds, r.exchanges
                    )
                    return r.narrow[:P].astype(np.int32)
            if handle_matches is not None:
                # Inline dispatch needs concrete per-stream buffers:
                # leaving the roster materializes this stream's row
                # (ownership moves back from the batch to the engine;
                # the next coalesced wave re-stacks and re-locks).
                resident = resident.materialize()
            out = None
            if delta is not None:
                out = self._dispatch_delta(
                    delta, resident, limit, P, budget, pairs, rb_k
                )
                if out is None:
                    # The delta dispatch failed (injected delta.apply
                    # fault, scatter error): the resident buffers may
                    # already have been donated into the failed call,
                    # so re-sync dense through the table-BUILD variant
                    # — it needs only host state, and its outputs
                    # re-seed the resident lag buffer for the next
                    # epoch's delta.
                    observe_pack_shift(
                        ("warm_fused_build", lags.shape, C),
                        int(payload.dtype.itemsize) * 8,
                    )
                    self._m_h2d_dense.inc(payload.nbytes)
                    out = _warm_fused_build(
                        payload, choice.astype(np.int32), limit,
                        num_consumers=C, iters=budget, max_pairs=pairs,
                        exchange_budget=budget, bucket=B,
                    )
                else:
                    # Divergence check — the conservation law: refine
                    # permutes ownership, never lag mass, so the device
                    # totals must sum to the host lag sum exactly
                    # (int64, wrap-consistent on both sides).  A
                    # mismatch means the resident lag buffer diverged
                    # from the mirror — re-sync dense on the delta's
                    # own successors (assignment validity is preserved
                    # by construction; only quality could be off).
                    if int(np.asarray(out[5]).sum()) != lag_sum:
                        LOGGER.warning(
                            "delta epoch diverged from the host lag "
                            "sum; re-syncing with a dense upload"
                        )
                        self._m_delta["fallback"].inc()
                        # Quarantine-plane accounting (utils/scrub):
                        # the graceful in-request lane of the same
                        # integrity story — the lag state diverged and
                        # was rebuilt from host truth, just without a
                        # failed request.
                        scrub_mod.record_quarantine(
                            ["lags"], "resynced", source="delta"
                        )
                        self._m_h2d_dense.inc(payload.nbytes)
                        # Same delta_k as the warmed signature (an
                        # incident-time recompile would defeat the
                        # resync), but the tail diffs against the
                        # FAILED dispatch's exit choice — not the
                        # host's view — so it is unusable here.
                        rb_base = None
                        with self._collective_gate():
                            out = _warm_fused_resident(
                                payload, out[1], out[2], out[3], limit,
                                num_consumers=C, iters=budget,
                                max_pairs=pairs, exchange_budget=budget,
                                delta_k=rb_k,
                            )
                    else:
                        self._m_delta["applied"].inc()
            if out is None:
                self._m_h2d_dense.inc(payload.nbytes)
                with self._collective_gate():
                    out = _warm_fused_resident(
                        payload, resident[0], resident[1], resident[2],
                        limit, num_consumers=C, iters=budget,
                        max_pairs=pairs, exchange_budget=budget,
                        delta_k=rb_k,
                    )
        else:
            observe_pack_shift(
                ("warm_fused_build", lags.shape, C),
                int(payload.dtype.itemsize) * 8,
            )
            self._m_h2d_dense.inc(payload.nbytes)
            out = _warm_fused_build(
                payload, choice.astype(np.int32), limit,
                num_consumers=C, iters=budget, max_pairs=pairs,
                exchange_budget=budget, bucket=B,
            )
        (narrow, choice_p, row_tab, counts, lags_p, totals, rounds, ex,
         digest) = out[:9]
        if len(out) > 9 and rb_base is not None:
            # O(changed) readback (ops/delta): fetch only the compaction
            # tail + digest — bytes scale with the churn bound, not P.
            # The digest still gates adoption AND the served answer,
            # exactly like the dense fetch below.
            with metrics.device_phase("refine"):
                d_idx, d_vals, d_n, digest_np = jax.device_get(
                    (out[9], out[10], out[11], digest)
                )
            n = int(d_n)
            if n <= rb_k:
                self._verify_digest(digest_np, P, lag_sum, source="epoch")
                self._m_d2h_delta.inc(d_idx.nbytes + d_vals.nbytes + 4)
                self._m_rb["applied"].inc()
                self._adopt_resident(
                    (choice_p, row_tab, counts, lags_p), lags
                )
                self._fill_stats_from_device(
                    stats, totals, counts, rounds, ex
                )
                return apply_assignment_delta(rb_base, d_idx, d_vals, n)
            # Churn exceeded the static K bound (possible only off the
            # budgeted bulk path): the dense narrow vector is already
            # computed device-side — a second fetch, never a
            # re-dispatch.
            self._m_rb["overflow"].inc()
        elif len(out) > 9:
            # Tail present but diffed against device-internal state
            # (resync fallback): count the epoch against the readback
            # ladder's fallback outcome, fetch dense.
            self._m_rb["fallback"].inc()
        # ONE device fetch for the answer AND its digest: the narrow
        # readback blocks on the dispatch anyway, so the integrity
        # check's marginal per-epoch cost is the 32-byte ride-along
        # plus a few host comparisons (the bench's <1%-of-noop gate).
        # The `refine` device phase covers the blocking fetch — i.e.
        # the refine executable INCLUDING its readback (the dispatch
        # above is async; documented in DEPLOYMENT.md "Kernel plane").
        with metrics.device_phase("refine"):
            narrow_np, digest_np = jax.device_get((narrow, digest))
        self._m_d2h_dense.inc(narrow_np.nbytes)
        # THE per-epoch integrity gate (utils/scrub): the fused digest
        # must match host truth before the successors are adopted or
        # the answer served — a mismatch quarantines the stream and the
        # request falls back to the degraded ladder, never the corrupt
        # buffer.
        self._verify_digest(digest_np, P, lag_sum, source="epoch")
        self._adopt_resident((choice_p, row_tab, counts, lags_p), lags)
        self._fill_stats_from_device(stats, totals, counts, rounds, ex)
        return narrow_np.astype(np.int32)

    def _effective_delta_fraction(self) -> float:
        """The delta/dense cutoff in force for the next epoch: the
        global ``delta_max_fraction`` knob until the bounded window
        holds enough samples, then ``q90 * margin`` of this stream's
        observed fractions — clamped to [knob/4, min(2*knob, 0.5)] so
        a noisy window can neither disable the delta path nor push a
        padded upload past the byte-win regime (the strict byte gate
        and the warmed K ladder still bind independently)."""
        base = self.delta_max_fraction
        if not (self.delta_adaptive and self.delta_enabled):
            return base
        w = self._churn_fractions
        if len(w) < _ADAPT_MIN_SAMPLES:
            return base
        q = sorted(w)[int(_ADAPT_QUANTILE * (len(w) - 1))]
        hi = min(2.0 * base, 0.5)
        lo = base / 4.0
        return float(min(max(_ADAPT_MARGIN * q, lo), hi))

    def _delta_plan(self, lags: np.ndarray, payload):
        """Build this epoch's padded (idx, vals) delta against the host
        lag mirror, or None when the epoch must upload dense: delta
        mode off, no mirror (cold/churn/recovery — those paths re-seed
        it), the diff itself failed (fault point ``delta.diff``), the
        changed fraction exceeds ``delta_max_fraction``, the pow2 K
        bucket exceeds the warmed ladder, or the padded delta would not
        actually be smaller than the dense payload.  Returns
        ``(idx int32[K], vals int64[K], upload_bytes, n_changed)``."""
        if not self.delta_enabled:
            return None
        mirror = self._lag_mirror
        if mirror is None or mirror.shape[0] != lags.shape[0]:
            return None
        try:
            faults.fire("delta.diff")
            changed = np.flatnonzero(lags != mirror)
        except Exception:  # noqa: BLE001 — dense is the safe fallback
            LOGGER.warning(
                "delta diff failed; uploading dense", exc_info=True
            )
            self._m_delta["fallback"].inc()
            return None
        n = int(changed.size)
        P = lags.shape[0]
        # Feed the adaptive window with the OBSERVED fraction (whatever
        # the outcome) so the cutoff tracks this stream's real churn
        # distribution, then gate on the epoch-start effective cutoff.
        self._churn_fractions.append(n / max(P, 1))
        K = delta_bucket(n)
        if (
            n > self.last_effective_delta_fraction * P
            or K > self._delta_kmax
            or K * _DELTA_ENTRY_BYTES >= payload.nbytes
        ):
            self._m_delta["fallback"].inc()
            return None
        idx = np.zeros(K, dtype=np.int32)
        idx[:n] = changed
        # Padding entries write index 0's NEW value: identical to the
        # real delta's write when index 0 changed, identical to the
        # current resident value when it did not — either way a no-op,
        # never a conflicting duplicate scatter.
        vals = np.full(K, int(lags[0]), dtype=np.int64)
        vals[:n] = lags[changed]
        return idx, vals, idx.nbytes + vals.nbytes, n

    def _dispatch_delta(
        self, delta, resident, limit, P: int, budget: int, pairs,
        rb_k: int = 0,
    ):
        """One fused delta dispatch over the resident 4-tuple; returns
        the executable's output tuple, or None when the dispatch failed
        (fault point ``delta.apply`` fires first — the caller re-syncs
        dense within the same epoch, warm host state intact).  ``rb_k``
        threads the O(changed) readback width through (ops/delta)."""
        idx, vals, nbytes, n = delta
        try:
            faults.fire("delta.apply")
            with metrics.span("stream.h2d_delta"), self._collective_gate():
                out = _warm_fused_delta(
                    idx, vals, resident[3], resident[0], resident[1],
                    resident[2], limit, P=P,
                    num_consumers=self.num_consumers, iters=budget,
                    max_pairs=pairs, exchange_budget=budget,
                    delta_k=rb_k,
                )
        except Exception:  # noqa: BLE001 — dense re-sync is the contract
            LOGGER.warning(
                "delta apply failed (%d changed); falling back to a "
                "dense upload", n, exc_info=True,
            )
            self._m_delta["fallback"].inc()
            return None
        self._m_h2d_delta.inc(nbytes)
        return out

    def _fill_stats_from_device(
        self, stats: StreamingStats, totals, counts, rounds, ex
    ) -> None:
        """Quality stats from the fused executable's own accumulators —
        exact int64, so no 2^53 fallback is needed (the device totals ARE
        the scatter-add the host bincount approximates)."""
        totals = np.asarray(totals)
        counts = np.asarray(counts)
        mean = totals.mean()
        stats.max_mean_imbalance = float(totals.max() / mean) if mean else 1.0
        stats.count_spread = int(counts.max() - counts.min())
        stats.refine_rounds = int(rounds)
        stats.refine_exchanges = int(ex)

    def _fill_quality_stats(
        self,
        stats: StreamingStats,
        choice: np.ndarray,
        lags: np.ndarray,
        bound: float,
        exact_bincount: bool,
    ) -> None:
        """``bound`` and ``exact_bincount`` depend only on the epoch's lags
        — the caller computes them once per rebalance (a refined epoch
        evaluates stats twice, a guardrail trip three times)."""
        # Weighted bincount accumulates in f64: exact while the total lag
        # stays below 2^53 (every partial sum is then an exact integer) —
        # and ~10x faster than np.add.at at P=100k, which matters because
        # this evaluation IS the no-op-epoch fast path.  Beyond 2^53 fall
        # back to the exact scatter-add.
        if exact_bincount:
            totals = np.bincount(
                choice, weights=lags, minlength=self.num_consumers
            ).astype(np.int64)
        else:
            totals = np.zeros(self.num_consumers, dtype=np.int64)
            np.add.at(totals, choice.astype(np.int64), lags)
        counts = np.bincount(choice, minlength=self.num_consumers)
        mean = totals.mean()
        stats.max_mean_imbalance = float(totals.max() / mean) if mean else 1.0
        stats.count_spread = int(counts.max() - counts.min())
        # Count-constrained input bound (shared with the benchmark's
        # quality_ratio, see utils/observability.count_constrained_bound):
        # a count-forced peak is not read as warm-path quality drift.
        stats.imbalance_bound = bound

    def remap_members(
        self, old_to_new: np.ndarray, new_num_consumers: int
    ) -> None:
        """Carry warm state across a MEMBERSHIP change with bounded churn.

        Kafka rebalances are usually triggered by a member joining or
        leaving, and the reference — stateless — reshuffles from scratch
        (O(P) churn).  This keeps every surviving member's partitions in
        place: ``old_to_new[i]`` is consumer i's new dense index (-1 if it
        left; joiners simply extend the range).  Orphaned rows (owners who
        left) are re-seated by the next :meth:`rebalance`'s repair pass,
        and joiners fill via the same pass, so churn is bounded by
        ``orphans + capacity overflow + 2 * refine_iters`` instead of P.

        Call this between rebalances when the group membership changed;
        call :meth:`reset` instead to force a full re-solve.
        """
        old_to_new = np.ascontiguousarray(old_to_new, dtype=np.int32)
        if self._prev_choice is not None:
            prev = self._prev_choice
            valid = (prev >= 0) & (prev < old_to_new.shape[0])
            remapped = np.full(prev.shape[0], -1, dtype=np.int32)
            remapped[valid] = old_to_new[prev[valid]]
            self._prev_choice = remapped
        self._drop_resident()  # device state predates the remap
        self.num_consumers = int(new_num_consumers)

    def _repair_choice(self, choice: np.ndarray, lags: np.ndarray):
        """Seat unowned rows and enforce the count invariant host-side.

        After :meth:`remap_members`, some rows are orphaned (-1) and the
        surviving members' counts may exceed the new ceiling
        ``ceil(P / C)``.  Overflowing owners release their SMALLEST-lag
        rows (cheapest churn); then orphans, largest lag first, go to the
        least-loaded open consumer — the count-primary greedy rule over
        only the moving rows, O(moving * C) host work on a few hundred
        rows, versus a full device re-solve.  A final correction pass
        restores ``max - min <= 1`` exactly: with a non-divisible P the
        cap-based release alone leaves every survivor at ceil while the
        joiner cannot reach floor (e.g. P=401, C 4->5: cap 81, survivors
        81,81,81,81, joiner 77 — spread 4, found by the
        operation-sequence fuzz; a join can also arrive with no cap
        overflow at all, e.g. counts 2,2,2,2,2,0), and the count
        invariant is the reference's PRIMARY semantic, so it must hold
        even when the quality threshold later skips the refine.

        Owns its trigger: returns ``(choice unchanged, 0)`` when there is
        nothing to repair.  Returns ``(repaired choice, rows moved)``.
        """
        C = self.num_consumers
        P = lags.shape[0]
        cap = -(-P // C)  # ceil: no consumer may exceed the new ceiling
        counts = np.bincount(choice[choice >= 0], minlength=C)
        has_orphans = bool((choice < 0).any())
        if (
            not has_orphans
            and counts.max() <= cap
            and counts.max() - counts.min() <= 1
        ):
            return choice, 0
        original = choice
        choice = choice.copy()
        totals = np.zeros(C, dtype=np.int64)
        sel = choice >= 0
        np.add.at(totals, choice[sel], lags[sel])
        # Release overflow (smallest lag first -> cheapest to move).
        for c in np.nonzero(counts > cap)[0]:
            rows = np.nonzero(choice == c)[0]
            release = rows[np.argsort(lags[rows])][: counts[c] - cap]
            choice[release] = -1
            counts[c] = cap
            totals[c] -= lags[release].sum()
        def least_total_of(cand: np.ndarray) -> int:
            """THE seating tie-break: least total lag among the candidate
            mask (shared by orphan seating and spread correction)."""
            return int(
                np.argmin(np.where(cand, totals, np.iinfo(np.int64).max))
            )

        # Seat orphans: largest lag first, least (count, total) open seat.
        orphans = np.nonzero(choice < 0)[0]
        for p in orphans[np.argsort(-lags[orphans])]:
            open_mask = counts < cap
            key = np.where(open_mask, counts, np.iinfo(np.int64).max)
            who = least_total_of(key == key.min())
            choice[p] = who
            counts[who] += 1
            totals[who] += lags[p]
        # Spread correction: move the heaviest-count member's smallest-lag
        # row to the lightest member until max - min <= 1.  Bounded by
        # O(C * initial spread) single-row moves.
        while counts.max() - counts.min() > 1:
            donor = int(np.argmax(counts))
            recv = least_total_of(counts == counts.min())
            rows = np.nonzero(choice == donor)[0]
            p = rows[np.argmin(lags[rows])]
            choice[p] = recv
            counts[donor] -= 1
            counts[recv] += 1
            totals[donor] -= lags[p]
            totals[recv] += lags[p]
        return choice, int((choice != original).sum())

    def export_state(self) -> Optional[np.ndarray]:
        """The engine's host-durable snapshot unit (utils/snapshot):
        a copy of the previous choice vector, or None while cold.
        Deliberately host-only — the device-resident (choice, table,
        counts) buffers (or a locked-roster handle) are NOT exported:
        they are rebuildable from this vector by the next refine
        dispatch, exactly the :meth:`seed_choice` contract recovery
        replays, so a snapshot never has to block on (or race) a
        device readback."""
        prev = self._prev_choice
        return None if prev is None else np.array(prev, copy=True)

    def seed_choice(self, choice: np.ndarray) -> None:
        """Warm-restart seed: adopt a host-side choice vector as the
        previous assignment (the degraded-mode ladder's recovery path —
        a poisoned stream restarts from the last answer the clients
        actually received instead of paying a full cold solve).  The
        device-resident state is left stale; the next refine dispatch
        rebuilds its tables from this host vector."""
        self._prev_choice = np.ascontiguousarray(choice, dtype=np.int32)
        self._drop_resident()

    @property
    def needs_dense_resync(self) -> bool:
        """True when the next warm epoch must rebuild the device state
        with a full dense upload (stale resident after seed_choice /
        repair / remap): the sidecar's resync pacer gates exactly
        these epochs so a restart wave cannot serialize the device
        behind one dense mega-wave (DEPLOYMENT.md "Restarts and
        recovery")."""
        return self._prev_choice is not None and self._resident is None

    def prestack_resident(self) -> bool:
        """Boot-time pre-stack (ROADMAP lifecycle (b)): rebuild the
        device-resident warm state from the seeded choice under a ZERO
        lag vector, off the serving path.  A zero vector meets any
        quality limit before the first exchange round, so the choice
        comes back UNCHANGED — the next real epoch is bit-identical to
        what the lazy inline rebuild would have produced — while the
        resident 4-tuple (choice, table, counts, lags) is already on
        device, making that epoch a resident (coalescible) dispatch
        instead of an inline dense table-build.  Uses the same statics
        as the serving warm build, so a warmed deployment compiles
        nothing here.  Returns True when a resident was built."""
        if self._prev_choice is None or self._resident is not None:
            return False
        ensure_x64()
        P = int(self._prev_choice.shape[0])
        lags = np.zeros(P, dtype=np.int64)
        payload, _ = stream_payload(lags)
        out = _warm_fused_build(
            payload, self._prev_choice.astype(np.int32), 0.0,
            num_consumers=self.num_consumers, iters=self.refine_iters,
            max_pairs=min(self.num_consumers // 2, 16),
            exchange_budget=self.refine_iters, bucket=self._bucket(P),
        )
        self._verify_digest(out[8], P, 0, source="prestack")
        self._adopt_resident(tuple(out[1:5]), lags)
        return True

    def reset(self) -> None:
        """Drop warm state (force the next rebalance to solve cold)."""
        self._prev_choice = None
        self._drop_resident()
