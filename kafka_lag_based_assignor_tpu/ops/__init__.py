"""TPU (JAX/XLA) assignment kernels."""
