"""Parallel pairwise-exchange refinement kernel.

Post-processes any integral, count-balanced assignment to tighten the
north-star metric (max/mean lag imbalance) beyond what one greedy pass can
reach, while preserving the count invariant ``max - min <= 1``.

TPU-native design: instead of one exchange per step (a sequential local
search with a P-sized sort in every iteration), each **round** performs up
to ``max_pairs`` *disjoint* exchanges simultaneously:

1. rank consumers by load (one C-sized argsort — C << P) and pair the
   k-th most-loaded consumer with a partner from the light half, rotating
   the partner permutation every round so a stuck heavy consumer meets
   every possible partner across rounds;
2. for every pair independently, pick the best single-partition **move**
   (heavy → light, lag closest to half the load gap, only while the count
   spread stays <= 1) and the best **swap** — light rows and heavy-side
   *queries* are co-sorted in ONE packed-key sort (pair id in the high
   bits, quantized lag, a side bit), after which each heavy row's best
   swap counterparts are its nearest light neighbours in sort order,
   found with two cumulative scans (no searchsorted, no second sort);
3. move and swap candidates merge into a single score stream (a tag bit
   under the score keeps ties preferring moves), so ONE sort-based
   segmented argmin picks each pair's exchange; apply every
   strictly-improving exchange at once.  Pairs are disjoint (each
   consumer belongs to at most one), so parallel application is
   race-free, and since any transferred amount d satisfies
   0 < d < load_heavy - load_light, no consumer's load ever exceeds the
   running maximum — the global max is monotone non-increasing.

A round is therefore TWO P-sized sorts (the combined neighbour sort and
the segmented argmin) plus cumulative scans, elementwise ops, and a few
gathers — versus the previous generation's five sort passes
(light-key sort, a 2P sort-based searchsorted, and two segmented
argmins); fetch-synchronized probes on the target TPU
(retired probe, git history — ``block_until_ready`` is NOT a valid clock on
this platform) put a P=131072 sort at ~0.4 ms, making op count, not
element count, the budget.  Churn is bounded by ``2 * iters * max_pairs``.

Candidate *selection* works on quantized values; validity is enforced by
STRICT quantized inequalities that imply the exact ones (see the safety
lemma below): quantization can only MISS boundary candidates, never admit
a worsening exchange.  With the single 48-bit value field the quantization
shift is 0 (exact selection) for any lag below 2^48.  The amounts actually
applied to the load accumulators are exact int64, gathered at the [K]
winners.

SAFETY LEMMA (why strict quantized validity implies exact validity, for
non-negative a, b, diff and any shift s — there is NO exact recheck
downstream for swaps, this argument is the whole guarantee):
  d_q > 0:       a>>s > b>>s  ⟹  a >= ((b>>s)+1)<<s > b, so d > 0.
  d_q < diff_q:  write a = (a>>s)<<s + ra, b = (b>>s)<<s + rb,
    diff = (diff>>s)<<s + rd with 0 <= ra, rb, rd < 2^s.  Then
    d = a - b = (d_q<<s) + ra - rb < (d_q + 1)<<s <= (diff>>s)<<s
    <= diff.  So d < diff.
Hence a selected swap satisfies 0 < d < diff exactly — the monotone
non-increasing max is preserved.  (Moves check 0 < lag < diff on the
exact lag directly.)

The refinement is solver-agnostic: it accepts the (choice, lags) pair in
input order from the greedy kernels or the Sinkhorn rounding.  It
intentionally does NOT reproduce reference semantics — it is the framework's
quality mode (BASELINE config 4); parity solvers remain bit-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .sortops import bincount_sorted, segment_argmin_first, segment_sum

_PAIR_BITS = 14  # pair-id field width in the packed keys
_VBITS = 63 - _PAIR_BITS - 1  # quantized-lag field width (48)
# Score sentinel (fits (x << 1) | 1 in int64).  A plain Python int on
# purpose: a module-level ``jnp.int64(...)`` would be created EAGERLY at
# import time, and if the importer has not enabled x64 yet it silently
# truncates to int32 garbage (observed: every exchange candidate scored
# "valid" 0 and the kernel became a no-op).  As a Python int it converts
# at trace time, after the entry points' ensure_x64().
_SBIG_INT = 1 << 60


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs",
                              "patience")
)
def refine_assignment(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 16,
    max_pairs: int | None = None,
    patience: int = 8,
):
    """Improve an integral assignment by rounds of parallel exchanges.

    Args:
      lags: [P] lag per partition row (non-negative, contract §2.4.6).
      valid: [P] mask; invalid rows must have choice == -1.
      choice: int32[P] consumer index per row (count-balanced).
      num_consumers: static C.
      iters: refinement rounds; each applies up to ``max_pairs`` disjoint,
        strictly-improving exchanges (or no-ops once converged).
      max_pairs: concurrent consumer pairs per round (default C // 2).
        Total churn is bounded by ``2 * iters * max_pairs`` partitions.
      patience: adaptive budget — stop early once this many CONSECUTIVE
        rounds failed to reduce the MAXIMUM consumer load.  The metric is
        max/mean and the mean is invariant (total lag is conserved), so
        only peak reduction counts as progress; exchanges between
        non-peak pairs matter only as enablers of a later peak reduction,
        and ``patience`` rounds of a stuck peak (the heaviest consumer
        meets a different rotated partner each round) make further
        progress unlikely.  Early stop only ever reduces churn, so the
        documented churn bound still holds.

    Returns (choice int32[P], counts int32[C], totals[C]).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    if K >= (1 << _PAIR_BITS) - 1:
        raise ValueError(
            f"max_pairs={K} exceeds the packed pair-id field "
            f"({_PAIR_BITS} bits)"
        )
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangeP = jnp.arange(P, dtype=jnp.int32)
    key_big = jnp.iinfo(jnp.int64).max
    vmask = (jnp.int64(1) << _VBITS) - 1
    sbig = jnp.asarray(_SBIG_INT, jnp.int64)

    choice = choice.astype(jnp.int32)
    assigned = valid & (choice >= 0)
    seg0 = jnp.where(assigned, choice, -1)
    totals0 = segment_sum(jnp.where(assigned, lags, 0), seg0, C)
    counts0 = bincount_sorted(seg0, C)
    if C < 2:
        return choice, counts0, totals0

    # Quantization shift: the 48-bit value field holds any lag below 2^48
    # exactly (shift 0); larger lags shift just enough to fit.  Selection
    # compares live in the shifted domain; strictness makes them sound
    # (safety lemma, module docstring).  Shared with the resident core
    # (_quant_shift) so both score candidates identically.
    pshift = _quant_shift(lags, assigned)

    def body(state):
        it, since, choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Rank consumers by load.  Pair the k-th heaviest with a partner
        # from the light half, rotating the partner permutation each round
        # (a bijection on the light half, so pairs stay disjoint).
        order = jnp.argsort(totals).astype(jnp.int32)  # ascending
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        n_light = C - K
        shift = it % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]             # [K]
        heavy = order[C - 1 - jnp.arange(K)]  # [K]
        diff = totals[heavy] - totals[light]  # [K] >= 0

        # Per-consumer combo table -> ONE P-sized gather for pair id,
        # side, and the move-permission bit (moves must keep the count
        # spread <= 1, a per-pair property known before selection).
        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            rank < n_light,
            slot_to_pair[jnp.clip(rank, 0, n_light - 1)],
            C - 1 - rank,
        )
        heavy_side = rank >= C - K
        move_ok_pair = counts[heavy] > counts[light]  # [K]
        move_ok_of = jnp.where(
            heavy_side,
            jnp.pad(move_ok_pair, (0, 1))[jnp.clip(pair_of, 0, K)],
            False,
        )
        combo_tab = (
            pair_of
            | (heavy_side.astype(jnp.int32) << _PAIR_BITS)
            | (move_ok_of.astype(jnp.int32) << (_PAIR_BITS + 1))
        )
        combo = jnp.where(assigned, combo_tab[safe_choice], -1)
        k_p = combo & ((1 << _PAIR_BITS) - 1)
        row_heavy = (combo >> _PAIR_BITS) & 1
        row_move_ok = (combo >> (_PAIR_BITS + 1)) & 1
        participates = (combo >= 0) & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = jnp.where(participates, diff[kc], 0)
        delta_p = diff_p >> 1  # diff >= 0, so >>1 == //2

        # THE round sort: light rows keyed by their own quantized lag,
        # heavy rows keyed by their ideal counterpart lag (lag - delta),
        # pair id in the high bits, side bit last (equal-valued lights
        # sort before the heavy query).  After this one sort each heavy
        # row's best swap counterparts are its nearest light neighbours.
        qself = lags >> pshift
        tgt = jnp.clip(lags - delta_p, 0, None) >> pshift
        qval = jnp.where(row_heavy == 1, tgt, qself)
        key = jnp.where(
            participates,
            (k_p.astype(jnp.int64) << (_VBITS + 1))
            | (jnp.clip(qval, 0, vmask) << 1)
            | row_heavy.astype(jnp.int64),
            key_big,
        )
        skey, slag, srow, smove_ok = lax.sort(
            (key, lags, arangeP, row_move_ok), num_keys=1
        )

        part_s = skey < key_big
        pair_s = (skey >> (_VBITS + 1)).astype(jnp.int32)
        heavy_s = part_s & ((skey & 1) == 1)
        light_s = part_s & ((skey & 1) == 0)
        qlag_s = slag >> pshift
        diff_s = jnp.where(
            heavy_s, diff[jnp.clip(pair_s, 0, K - 1)], 0
        )
        delta_q_s = (diff_s >> 1) >> pshift
        diff_q_s = diff_s >> pshift

        # Nearest light neighbours via cumulative scans (replaces the
        # previous sort-based searchsorted): prev = last light at or
        # below, nxt = first light above.  A neighbour from another pair
        # fails the pair check below, exactly like a searchsorted landing
        # at a pair boundary did.
        prev_l = lax.cummax(jnp.where(light_s, arangeP, -1))
        nxt_l = lax.cummin(
            jnp.where(light_s, arangeP, P), reverse=True
        )

        def neighbour(nb):
            inb = jnp.clip(nb, 0, P - 1)
            nkey = skey[inb]  # one P-sized gather per neighbour
            okq = (
                (nb >= 0) & (nb < P)
                & ((nkey & 1) == 0)
                & ((nkey >> (_VBITS + 1)).astype(jnp.int32) == pair_s)
            )
            d_q = qlag_s - ((nkey >> 1) & vmask)
            ok = heavy_s & okq & (d_q > 0) & (d_q < diff_q_s)
            return jnp.where(ok, jnp.abs(d_q - delta_q_s), sbig)

        err_a = neighbour(prev_l)
        err_b = neighbour(nxt_l)
        use_b = err_b < err_a
        err_swap = jnp.where(use_b, err_b, err_a)
        nb_sel = jnp.where(use_b, nxt_l, prev_l)

        # Move candidate (exact validity on the resident lag) merged with
        # the swap via a tag bit under the score: ties prefer the move.
        ok_move = (
            heavy_s & (smove_ok == 1) & (slag > 0) & (slag < diff_s)
        )
        score_move = jnp.where(
            ok_move, jnp.abs(qlag_s - delta_q_s), sbig
        )
        combined = jnp.where(
            score_move <= err_swap,
            score_move << 1,
            (err_swap << 1) | 1,
        )
        seg_h = jnp.where(heavy_s, pair_s, K)
        minv, widx = segment_argmin_first(combined, seg_h, K, P)

        # Decode the [K] winners; all remaining work is K-sized.
        do = minv < (sbig << 1)
        is_swap = (minv & 1) == 1
        wclip = jnp.clip(widx, 0, P - 1)
        p_sel = srow[wclip]
        lag_p = slag[wclip]
        nb_k = jnp.clip(nb_sel[wclip], 0, P - 1)
        q_sel = srow[nb_k]
        lag_q = slag[nb_k]
        use_swap = do & is_swap
        d = jnp.where(use_swap, lag_p - lag_q, lag_p)
        d = jnp.where(do, d, 0)

        # Apply all exchanges at once (pairs are disjoint -> race-free);
        # K-sized scatters, cost proportional to the K updates.
        upd_p = jnp.where(do, p_sel, P)
        upd_q = jnp.where(use_swap, q_sel, P)
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d).at[light].add(d)
        dc = (do & ~is_swap).astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, 0, since + 1)
        return it + 1, new_since, new_choice, new_totals, new_counts

    def cond(state):
        it, since = state[0], state[1]
        return (it < iters) & (since < patience)

    _, _, choice, totals, counts = lax.while_loop(
        cond,
        body,
        (jnp.int32(0), jnp.int32(0), choice, totals0, counts0),
    )
    return choice, counts, totals


# ---------------------------------------------------------------------------
# Resident-table refinement: the fused warm-path core.
#
# The round body above pays TWO P-sized sorts per round (the co-sorted
# neighbour sort and the segmented argmin) — measured at ~35 ms/round at
# the 100k north star on the CPU backend, which made a 23-round warm
# dispatch cost 40x a cold solve (BENCH_r05, VERDICT r5 item 4).  The
# resident formulation replaces both P-sorts with a [C, M] row-index
# TABLE (M = ceil(P/C) + 1 slots per consumer) built by ONE P-sized sort
# per dispatch (or carried device-resident across dispatches by the
# streaming engine): each round then touches only the 2K participating
# consumers' segments — a [K, M] slice sort plus a searchsorted — so the
# per-round cost is O(K * M log M) instead of O(P log P).
#
# Selection is BIT-IDENTICAL to :func:`refine_assignment`'s exact-argmin
# (CPU) semantics: the same quantized candidate scores, the same
# nearest-neighbour swap restriction (prev = max (qval, row) light at or
# below the target, next = min (qval, row) light above — exactly the
# cummax/cummin neighbours of the co-sorted order), the same move/swap
# tag-bit merge, and the same (score, target, row) winner tie-break the
# stable sort + segmented argmin produce.  Pinned by the differential
# fuzz in tests/test_refine_resident.py.
#
# Beyond parity, the resident loop adds two OPT-IN early exits the warm
# path needs (both off in parity mode):
#   * ``quality_limit`` (dynamic scalar): stop once the peak consumer
#     total is at or below the limit — "refine until the target is met,
#     not until the budget is gone" — and, while running, let only pairs
#     whose HEAVY consumer is still above the limit exchange, so churn
#     and budget are spent exclusively on consumers that actually breach
#     the target (near-balanced pairs' cosmetic exchanges would
#     otherwise starve a stubborn peak of its budget);
#   * ``exchange_budget`` (static): count APPLIED exchanges instead of
#     charging rounds * pairs up front, so a concentrated-drift epoch can
#     spend its whole churn budget on one stubborn peak across many cheap
#     rounds.  Churn stays bounded by 2 * exchange_budget.
#
# PRECONDITION: per-consumer row counts must fit the table
# (max count <= table_rows — guaranteed by the count invariant
# ``max - min <= 1`` every production start satisfies).  Out-of-contract
# unbalanced inputs must use :func:`refine_assignment`.
# ---------------------------------------------------------------------------


def _quant_shift(lags, assigned):
    """The quantization shift of :func:`refine_assignment`, shared so the
    resident core scores candidates identically."""
    maxlag = jnp.maximum(jnp.max(jnp.where(assigned, lags, 0)), 1)
    bitlen = 64 - lax.clz(maxlag.astype(jnp.int64))
    return jnp.maximum(bitlen - _VBITS, 0).astype(jnp.int64)


def build_choice_tables(lags, valid, choice, num_consumers: int,
                        table_rows: int):
    """ONE P-sized stable sort -> compact per-consumer row-index table.

    Returns (row_tab int32[C, M] — row indices, sentinel P at empty
    slots — counts int32[C], totals int64-like[C]).  Rows within a
    consumer's segment appear in ascending row order (the stable sort's
    tie rule); the round body does not rely on any intra-segment order.
    """
    C, M = int(num_consumers), int(table_rows)
    P = lags.shape[0]
    arangeP = jnp.arange(P, dtype=jnp.int32)
    assigned = valid & (choice >= 0)
    seg = jnp.where(assigned, choice, C).astype(jnp.int32)
    sseg, srow = lax.sort((seg, arangeP), num_keys=1)
    bnd = jnp.searchsorted(
        sseg, jnp.arange(C + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    counts = bnd[1:] - bnd[:-1]
    pos = arangeP - bnd[jnp.clip(sseg, 0, C)]
    flat = jnp.where(
        (sseg < C) & (pos < M), sseg * M + pos, jnp.int32(C * M)
    )
    row_tab = (
        jnp.full((C * M,), P, jnp.int32)
        .at[flat]
        .set(srow, mode="drop")
        .reshape(C, M)
    )
    lag_tab = jnp.where(
        jnp.arange(M, dtype=jnp.int32)[None, :] < counts[:, None],
        lags[jnp.clip(row_tab, 0, P - 1)],
        0,
    )
    return row_tab, counts, lag_tab.sum(axis=1)


def refine_rounds_resident(
    lags,
    choice,
    row_tab,
    counts,
    totals,
    num_consumers: int,
    iters: int,
    max_pairs: int | None = None,
    patience: int = 8,
    exchange_budget: int = 0,
    quality_limit=None,
    bulk_transfer: bool = False,
    fan: int = 1,
    allow_moves: bool = True,
):
    """Traced resident-table round loop (see the section comment above).

    ``allow_moves`` (static, parity body only — the bulk rounds are
    swap-only by construction) disables the count-changing MOVE
    candidates so the loop is strictly count-preserving: the federated
    weighted-shard rounding (ops/fedsolve) seats capacity-weighted
    per-consumer counts that an exchange refinement must tighten for
    load WITHOUT eroding back toward uniform counts.

    ``choice``/``row_tab``/``counts``/``totals`` are the loop-carried
    state (the streaming engine keeps them device-resident between
    dispatches); ``quality_limit`` is a dynamic scalar peak-total bound
    (None or a negative value disables it), ``exchange_budget`` a static
    applied-exchange cap (0 disables — rounds * pairs semantics like
    :func:`refine_assignment`).

    ``bulk_transfer`` (static, the warm engine's round type) replaces the
    best-single-exchange selection with ANTI-RANKED BULK SWAPS: each
    pair sorts the heavy consumer's segment lag-descending and the light
    one's lag-ascending, matches the ranks (largest movable row against
    smallest), and applies the positive-gap swaps largest-gap-first
    while the cumulative transfer stays under ALL of: the half-gap (the
    global max stays monotone non-increasing — every light total ends
    strictly below its pair's old heavy total), the receiver's headroom
    to the limit (nobody is pushed past the target), and the heavy
    consumer's remaining distance to ``quality_limit`` (churn is not
    spent past the target).  A stubborn peak that needs ~100 single
    exchanges — ~100 sequential rounds under the one-exchange rule —
    drains in a handful of bulk rounds at the same churn bound (each
    swap still counts 1 exchange, 2 moved rows), and a round counts
    toward ``patience`` unless it closed >= 1/16 of the peak's
    remaining distance.  Selection quality is deliberately coarser than
    the parity mode's delta-closest rule; the quality limit, not bit
    parity, is this mode's contract.

    ``fan`` (static, bulk mode only) clones each heavy consumer across
    that many pairs in one round, striping its table slots so the clones
    trade DISJOINT rows with ``fan`` different light partners
    simultaneously.  One light partner's smallest rows can absorb only
    so much per round; a peak that must hand off nearly its whole
    inventory (e.g. one huge unmovable partition plus many small rows)
    drains ``fan`` partners' worth per round instead.  Per-clone
    crossing targets ``needed / fan``, so the clones cannot jointly
    overshoot the limit by more than fan extra swaps.

    Returns (choice, row_tab, counts, totals, rounds_done,
    exchanges_done).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    M = row_tab.shape[1]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    zero32 = jnp.int32(0)
    if C < 2 or iters <= 0:
        return choice, row_tab, counts, totals, zero32, zero32
    sbig = jnp.asarray(_SBIG_INT, jnp.int64)
    bigq = jnp.iinfo(jnp.int64).max
    choice = choice.astype(jnp.int32)
    pshift = _quant_shift(lags, choice >= 0)
    n_light = C - K
    kk = jnp.arange(K, dtype=jnp.int32)
    mslots = jnp.arange(M, dtype=jnp.int32)
    if quality_limit is None:
        quality_limit = -1.0
    limit = jnp.asarray(quality_limit, jnp.float64)

    def body(state):
        it, since, ex_done, choice, tab, counts, totals = state
        order = jnp.argsort(totals).astype(jnp.int32)
        shift = it % jnp.int32(n_light)
        light = order[(kk + shift) % n_light]  # [K]
        heavy = order[C - 1 - kk]              # [K]
        diff = totals[heavy] - totals[light]   # [K] >= 0
        move_ok = counts[heavy] > counts[light]
        if not allow_moves:
            move_ok = jnp.zeros_like(move_ok)
        delta = diff >> 1
        diff_q = diff >> pshift
        delta_q = delta >> pshift

        rows_h = tab[heavy]  # [K, M]
        rows_l = tab[light]
        hvalid = mslots[None, :] < counts[heavy][:, None]
        lvalid = mslots[None, :] < counts[light][:, None]
        lag_h = jnp.where(hvalid, lags[jnp.clip(rows_h, 0, P - 1)], 0)
        lag_l = jnp.where(lvalid, lags[jnp.clip(rows_l, 0, P - 1)], 0)
        qlag_h = lag_h >> pshift
        tgt_h = jnp.clip(lag_h - delta[:, None], 0) >> pshift

        # Light segments sorted by (qval, row): prev/next neighbours in
        # the co-sorted order of the oracle kernel are then searchsorted
        # hits (equal-valued lights sort before the heavy query there, so
        # side='right' reproduces the boundary exactly).
        sq, srow_l, sslot_l, slag_l = lax.sort(
            (
                jnp.where(lvalid, lag_l >> pshift, bigq),
                jnp.where(lvalid, rows_l, jnp.int32(P)),
                jnp.broadcast_to(mslots, (K, M)),
                lag_l,
            ),
            num_keys=2,
            dimension=1,
        )
        ins = jax.vmap(
            lambda s, q: jnp.searchsorted(s, q, side="right")
        )(sq, tgt_h).astype(jnp.int32)

        def neighbour(idx):
            ok_idx = (idx >= 0) & (idx < counts[light][:, None])
            i_c = jnp.clip(idx, 0, M - 1)
            d_q = qlag_h - jnp.take_along_axis(sq, i_c, axis=1)
            ok = (
                hvalid & ok_idx & (d_q > 0) & (d_q < diff_q[:, None])
            )
            return jnp.where(ok, jnp.abs(d_q - delta_q[:, None]), sbig), i_c

        err_a, ia = neighbour(ins - 1)
        err_b, ib = neighbour(ins)
        use_b = err_b < err_a
        err_swap = jnp.where(use_b, err_b, err_a)
        nb_i = jnp.where(use_b, ib, ia)

        ok_move = (
            hvalid & move_ok[:, None] & (lag_h > 0)
            & (lag_h < diff[:, None])
        )
        score_move = jnp.where(
            ok_move, jnp.abs(qlag_h - delta_q[:, None]), sbig
        )
        combined = jnp.where(
            score_move <= err_swap,
            score_move << 1,
            (err_swap << 1) | 1,
        )

        # Winner per pair: lexicographic min (combined, target, row) —
        # exactly the stable-sorted segmented argmin of the oracle.
        m1 = jnp.min(combined, axis=1)
        on1 = combined == m1[:, None]
        m2 = jnp.min(jnp.where(on1, tgt_h, bigq), axis=1)
        on2 = on1 & (tgt_h == m2[:, None])
        m3 = jnp.min(jnp.where(on2, rows_h, jnp.int32(P)), axis=1)
        win = jnp.argmax(on2 & (rows_h == m3[:, None]), axis=1).astype(
            jnp.int32
        )

        # Target-directed spending: with a quality limit set, a pair
        # whose heavy consumer already meets the target applies nothing
        # (its budget/churn belongs to the consumers still above it).
        # limit < 0 (parity mode / no target) keeps every pair active.
        active = totals[heavy].astype(jnp.float64) > limit
        do = (m1 < (sbig << 1)) & active
        if exchange_budget:
            # Exact budget adherence: admit winners heaviest-pair-first
            # until the remaining quota is spent (pairs are already
            # ordered heaviest to lightest).
            quota = jnp.int32(exchange_budget) - ex_done
            do &= jnp.cumsum(do.astype(jnp.int32)).astype(jnp.int32) <= quota
        is_swap = (m1 & 1) == 1
        take = lambda a, i: jnp.take_along_axis(  # noqa: E731
            a, i[:, None], axis=1
        )[:, 0]
        p_sel = take(rows_h, win)
        lag_p = take(lag_h, win)
        nb_sel = take(nb_i, win)
        q_sel = take(srow_l, nb_sel)
        lag_q = take(slag_l, nb_sel)
        q_slot = take(sslot_l, nb_sel)
        use_swap = do & is_swap
        d = jnp.where(use_swap, lag_p - lag_q, lag_p)
        d = jnp.where(do, d, 0)

        upd_p = jnp.where(do, p_sel, jnp.int32(P))
        upd_q = jnp.where(use_swap, q_sel, jnp.int32(P))
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d).at[light].add(d)
        dc = (do & ~is_swap).astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)

        # Table maintenance (pairs are consumer-disjoint -> the K-sized
        # scatters are race-free).  Swap: the two rows trade table slots.
        # Move: swap-with-last compaction on the heavy segment, append on
        # the light one (counts[light] < counts[heavy] <= M when a move
        # fires, so the append slot is in range).
        flat = tab.reshape(C * M)
        nop = jnp.int32(C * M)
        is_move = do & ~is_swap
        h_win = heavy * M + win
        h_last = heavy * M + counts[heavy] - 1
        last_row = flat[jnp.clip(h_last, 0, C * M - 1)]
        flat = flat.at[jnp.where(use_swap, h_win, nop)].set(
            q_sel, mode="drop"
        )
        flat = flat.at[jnp.where(use_swap, light * M + q_slot, nop)].set(
            p_sel, mode="drop"
        )
        flat = flat.at[jnp.where(is_move, h_win, nop)].set(
            last_row, mode="drop"
        )
        flat = flat.at[jnp.where(is_move, h_last, nop)].set(
            jnp.int32(P), mode="drop"
        )
        flat = flat.at[
            jnp.where(is_move, light * M + counts[light], nop)
        ].set(p_sel, mode="drop")

        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, zero32, since + 1)
        new_ex = ex_done + jnp.sum(do.astype(jnp.int32)).astype(jnp.int32)
        return (
            it + 1, new_since, new_ex, new_choice,
            flat.reshape(C, M), new_counts, new_totals,
        )

    fan_eff = max(1, min(int(fan), K))

    def bulk_body(state):
        it, since, ex_done, choice, tab, counts, totals = state
        order = jnp.argsort(totals).astype(jnp.int32)
        shift = it % jnp.int32(n_light)
        light = order[(kk + shift) % n_light]  # [K]
        # Each of the top ceil(K / fan) consumers appears in ``fan``
        # consecutive pairs, trading a DISJOINT stripe of its table
        # slots with each of its partners (duplicate indices in the
        # totals update accumulate; the row/table scatters never
        # collide because the stripes are disjoint).
        heavy = order[C - 1 - kk // fan_eff]
        diff = totals[heavy] - totals[light]
        delta = diff >> 1
        heavy_f = totals[heavy].astype(jnp.float64)
        active = heavy_f > limit
        # Remaining distance to the target, split across the clones so
        # they cannot jointly overshoot; int64.  With no target
        # (limit < 0) each clone takes its share of the HALF-GAP, so the
        # clones jointly step ~delta like a single classic exchange
        # round instead of 8x over-draining the peak.
        big64 = jnp.iinfo(jnp.int64).max
        needed = jnp.where(
            limit >= 0,
            jnp.ceil((heavy_f - limit) / fan_eff).astype(jnp.int64),
            delta // fan_eff + 1,
        )
        # The RECEIVER's headroom to the same target: transferring past
        # it would push the light consumer above the limit, minting a
        # new just-over-target consumer for a later round to fix — the
        # relapse grind that turned one broad-drift epoch into ~150
        # rounds before this cap existed.
        headroom = jnp.where(
            limit >= 0,
            jnp.floor(limit - totals[light].astype(jnp.float64))
            .astype(jnp.int64),
            big64,
        )
        cap = jnp.minimum(delta, jnp.maximum(headroom, 0))

        rows_h = tab[heavy]  # [K, M]
        rows_l = tab[light]
        hvalid = mslots[None, :] < counts[heavy][:, None]
        lvalid = mslots[None, :] < counts[light][:, None]
        lag_h = jnp.where(hvalid, lags[jnp.clip(rows_h, 0, P - 1)], -1)
        lag_l = jnp.where(
            lvalid, lags[jnp.clip(rows_l, 0, P - 1)],
            jnp.int64(big64),
        )
        # ANTI-ranked pairing: heavy's rows lag-DESCENDING against
        # light's rows lag-ASCENDING, so a rank trades the heavy
        # consumer's largest movable rows for the light one's smallest —
        # the largest positive gaps (and so the fewest swaps per unit
        # transferred) come first.  Like-ranked pairing stalls exactly
        # on the case that matters: a peak pinned by one huge unmovable
        # row whose REMAINING rows are no bigger than any partner's.
        # Ties sort by row id (num_keys=2) and clone stripes are taken
        # in SORTED-RANK space below, so the selection is independent of
        # the table's internal slot arrangement — a resident table
        # carried across dispatches picks exactly what a freshly built
        # one picks (pinned by the streaming consistency test).
        nh, hs_row, hs_slot = lax.sort(
            (-lag_h, rows_h, jnp.broadcast_to(mslots, (K, M))),
            num_keys=2, dimension=1,
        )
        la, ls_row, ls_slot = lax.sort(
            (lag_l, rows_l, jnp.broadcast_to(mslots, (K, M))),
            num_keys=2, dimension=1,
        )
        # Clone k works the sorted ranks r with r % fan == k % fan
        # (every clone of one heavy sees an interleaved spread of its
        # segment); its j-th stripe row meets the light's j-th smallest.
        Ms = -(-M // fan_eff)
        jj = jnp.arange(Ms, dtype=jnp.int32)
        gidx = jj[None, :] * fan_eff + (kk[:, None] % fan_eff)  # [K, Ms]
        in_seg = gidx < M
        gidx = jnp.minimum(gidx, M - 1)
        take2 = lambda a: jnp.take_along_axis(a, gidx, axis=1)  # noqa: E731
        hs_lag = -take2(nh)
        hs_row_s = take2(hs_row)
        hs_slot_s = take2(hs_slot)
        ls_lag = la[:, :Ms]
        ls_row_s = ls_row[:, :Ms]
        ls_slot_s = ls_slot[:, :Ms]
        rank_ok = (
            in_seg & (take2(nh) <= 0) & (ls_lag < big64)
            & active[:, None]
        )
        d = jnp.where(rank_ok, hs_lag - ls_lag, 0)  # anti-ranked gap
        # Largest gaps first; prefix-select while the cumulative
        # transfer stays under the per-pair cap AND the remaining
        # distance to the target (the crossing swap is admitted, so the
        # target is reached, not approached asymptotically).
        nd, dh_row, dh_slot, dl_row, dl_slot = lax.sort(
            (-d, hs_row_s, hs_slot_s, ls_row_s, ls_slot_s),
            num_keys=2, dimension=1,
        )
        ds = -nd
        # A gap larger than the per-pair cap can never be applied —
        # exclude it from the running total entirely, or one oversize
        # head entry would poison the cumulative sum and block every
        # smaller (perfectly applicable) swap behind it.
        fit = (ds > 0) & (ds <= cap[:, None])
        cum = jnp.cumsum(jnp.where(fit, ds, 0), axis=1)
        sel = (
            fit
            & (cum <= cap[:, None])
            & ((cum - ds) < needed[:, None])
        )
        if exchange_budget:
            flat_sel = sel.reshape(-1)
            quota = jnp.int32(exchange_budget) - ex_done
            flat_sel &= (
                jnp.cumsum(flat_sel.astype(jnp.int32)).astype(jnp.int32)
                <= quota
            )
            sel = flat_sel.reshape(K, Ms)

        transfer = jnp.sum(jnp.where(sel, ds, 0), axis=1)  # int64 [K]
        new_totals = totals.at[heavy].add(-transfer).at[light].add(
            transfer
        )
        nopP = jnp.int32(P)
        h_rows = jnp.where(sel, dh_row, nopP).reshape(-1)
        l_rows = jnp.where(sel, dl_row, nopP).reshape(-1)
        light_b = jnp.broadcast_to(light[:, None], (K, Ms)).reshape(-1)
        heavy_b = jnp.broadcast_to(heavy[:, None], (K, Ms)).reshape(-1)
        new_choice = choice.at[h_rows].set(light_b, mode="drop")
        new_choice = new_choice.at[l_rows].set(heavy_b, mode="drop")
        # Swaps are count-neutral: the two rows trade table slots.
        flat = tab.reshape(C * M)
        nop = jnp.int32(C * M)
        hidx = jnp.where(
            sel, heavy[:, None] * M + dh_slot, nop
        ).reshape(-1)
        lidx = jnp.where(
            sel, light[:, None] * M + dl_slot, nop
        ).reshape(-1)
        flat = flat.at[hidx].set(dl_row.reshape(-1), mode="drop")
        flat = flat.at[lidx].set(dh_row.reshape(-1), mode="drop")

        # Relative-progress patience: near the target the supply of
        # useful gaps dries up and rounds shave only a sliver off the
        # peak — churn spent on an asymptote.  A round counts as
        # progress only if it closed >= 1/16 of the peak's remaining
        # distance to the limit (with no limit set, any strict peak
        # drop counts, like the parity body).
        old_peak = jnp.max(totals).astype(jnp.float64)
        new_peak = jnp.max(new_totals).astype(jnp.float64)
        min_step = jnp.where(
            limit >= 0, (old_peak - limit) / 16.0, 0.0
        )
        good = (old_peak - new_peak) > jnp.maximum(min_step, 0.0)
        new_since = jnp.where(good, zero32, since + 1)
        new_ex = ex_done + jnp.sum(sel.astype(jnp.int32)).astype(
            jnp.int32
        )
        return (
            it + 1, new_since, new_ex, new_choice,
            flat.reshape(C, M), counts, new_totals,
        )

    def cond(state):
        it, since, ex_done = state[0], state[1], state[2]
        totals = state[6]
        go = (it < iters) & (since < patience)
        if exchange_budget:
            go &= ex_done < jnp.int32(exchange_budget)
        return go & (jnp.max(totals).astype(jnp.float64) > limit)

    it, _, ex_done, choice, row_tab, counts, totals = lax.while_loop(
        cond,
        bulk_body if bulk_transfer else body,
        (zero32, zero32, zero32, choice, row_tab, counts, totals),
    )
    return choice, row_tab, counts, totals, it, ex_done


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_consumers", "iters", "max_pairs", "patience",
        "exchange_budget",
    ),
)
def refine_assignment_resident(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 16,
    max_pairs: int | None = None,
    patience: int = 8,
    exchange_budget: int = 0,
    quality_limit=-1.0,
):
    """Drop-in :func:`refine_assignment` with the resident-table rounds.

    Same (choice, counts, totals) contract and — in the default
    configuration (no exchange budget, no quality limit) — bit-identical
    results to the oracle kernel's exact-argmin semantics; the table is
    built fresh per call (one P-sized sort) and discarded.  Requires the
    count invariant (max count <= ceil(P / C) + 1, see the section
    comment) — every production start satisfies it.
    """
    from .packing import table_rows

    C = int(num_consumers)
    choice = choice.astype(jnp.int32)
    if C < 2 or iters <= 0:
        assigned = valid & (choice >= 0)
        seg0 = jnp.where(assigned, choice, -1)
        from .sortops import bincount_sorted, segment_sum

        totals = segment_sum(jnp.where(assigned, lags, 0), seg0, C)
        return choice, bincount_sorted(seg0, C), totals
    row_tab, counts, totals = build_choice_tables(
        lags, valid, choice, C, table_rows(lags.shape[0], C)
    )
    choice, _, counts, totals, _, _ = refine_rounds_resident(
        lags, choice, row_tab, counts, totals, num_consumers=C,
        iters=iters, max_pairs=max_pairs, patience=patience,
        exchange_budget=exchange_budget, quality_limit=quality_limit,
    )
    return choice, counts, totals


# ---------------------------------------------------------------------------
# Resident-state integrity digest (the refine epilogue's seam)
# ---------------------------------------------------------------------------


def _state_digest_xla(lags_p, choice_p, counts, num_consumers: int):
    """XLA reference for the resident-state integrity digest — int64[4]
    ``[counts_sum, range_violations, lags_sum, counts_vs_choice_L1]``
    (see :mod:`..utils.scrub` for the host truths each slot must
    match).  A few reductions plus one bincount scatter on buffers the
    refine executable already holds.  All-integer arithmetic: the
    result is exact under ANY accumulation order, which is what lets
    the fused kernel epilogue replace this tree without a bit-parity
    caveat."""
    C = num_consumers
    in_range = (choice_p >= 0) & (choice_p < C)
    viol = ((choice_p < -1) | (choice_p >= C)).sum(dtype=jnp.int64)
    cnt = (
        jnp.zeros(C, jnp.int64)
        .at[jnp.where(in_range, choice_p, C)]
        .add(1, mode="drop")
    )
    mismatch = jnp.abs(cnt - counts.astype(jnp.int64)).sum(
        dtype=jnp.int64
    )
    return jnp.stack(
        [
            counts.sum(dtype=jnp.int64),
            viol,
            lags_p.sum(dtype=jnp.int64),
            mismatch,
        ]
    )


def _row_tab_lane_xla(lags_p, choice_p, row_tab, counts, num_consumers: int):
    """The row-TABLE integrity lane (int64 scalar, host truth 0): a
    slot-level checksum over the resident ``[C, M]`` row table —
    ROADMAP "state integrity" follow-on.  The first four lanes audit
    (lags, choice, counts); the table itself was previously audited
    only host-side by the scrubber, so a flipped table slot surfaced
    as a silently-misrouted refine, not a serving-time quarantine.

    Four all-integer violations summed into one lane (any one is
    nonzero exactly when the table diverged from the choice vector it
    mirrors, so a single bit flip anywhere in the table is caught):

    * a VALID slot (``j < counts[c]``) whose row index is outside
      ``[0, B)``;
    * a valid slot naming a row whose ``choice`` is not ``c``;
    * an EMPTY slot not holding the sentinel ``B``;
    * the checksum ``|sum(valid-slot row indices) - sum(assigned row
      indices)|`` — catches in-range flips that land on another row
      of the same consumer (the owner check alone would pass a
      duplicate entry)."""
    B = lags_p.shape[0]
    C, M = int(num_consumers), row_tab.shape[1]
    slot_j = jnp.arange(M, dtype=jnp.int32)[None, :]
    valid_slot = slot_j < jnp.minimum(counts, M)[:, None]
    r = jnp.clip(row_tab, 0, B - 1)
    owner_bad = (
        valid_slot & (choice_p[r] != jnp.arange(C, dtype=jnp.int32)[:, None])
    ).sum(dtype=jnp.int64)
    range_bad = (
        valid_slot & ((row_tab < 0) | (row_tab >= B))
    ).sum(dtype=jnp.int64)
    sentinel_bad = (~valid_slot & (row_tab != B)).sum(dtype=jnp.int64)
    slot_sum = jnp.where(valid_slot, r, 0).sum(dtype=jnp.int64)
    assigned = (choice_p >= 0) & (choice_p < C)
    row_sum = jnp.where(
        assigned, jnp.arange(B, dtype=jnp.int64), 0
    ).sum(dtype=jnp.int64)
    return owner_bad + range_bad + sentinel_bad + jnp.abs(
        slot_sum - row_sum
    )


def state_digest(lags_p, choice_p, counts, num_consumers: int,
                 row_tab=None):
    """THE digest seam: every refine epilogue (streaming's five fused
    executables and the coalesce path) computes the integrity digest
    through here.  Dispatch is decided at TRACE time from the
    probe-once device gate (:func:`.linear_ot_pallas.
    linear_pallas_available` — resolved by warm-up before the first
    trace; unprobed means False) plus host admission on the padded
    buffer shape; any trace-time kernel failure falls back to the XLA
    reduction tree and pins the digest kernel off for the process.
    The digest is all-integer, so both lowerings return identical
    bits (the device probe still verifies the real Mosaic lowering —
    int64 lanes are the risky part).

    ``row_tab`` extends the digest with a fifth lane — the row-TABLE
    slot checksum (:func:`_row_tab_lane_xla`, host truth 0) — so
    table corruption is caught at serving time, not only by the
    host-side scrubber.  The lane is an XLA reduction appended to
    whichever lowering produced the base four (the Pallas digest
    kernel's probe contract stays int64[4])."""
    from . import linear_ot_pallas as _lp

    base = None
    if _lp.linear_pallas_available(kind="digest") and _lp.digest_pallas_admit(
        int(lags_p.shape[0]), int(num_consumers)
    ):
        try:
            base = _lp.state_digest_pallas(
                lags_p, choice_p, counts, int(num_consumers)
            )
        except Exception as exc:  # noqa: L011 — verdict pinned off and
            # the failure logged (with the repr) by mark_linear_kernel_bad;
            # the XLA tree below serves the same exact digest.
            _lp.mark_linear_kernel_bad("digest", repr(exc))
    if base is None:
        base = _state_digest_xla(lags_p, choice_p, counts, num_consumers)
    if row_tab is None:
        return base
    lane = _row_tab_lane_xla(
        lags_p, choice_p, row_tab, counts, num_consumers
    )
    return jnp.concatenate([base, lane[None]])
