"""Parallel pairwise-exchange refinement kernel.

Post-processes any integral, count-balanced assignment to tighten the
north-star metric (max/mean lag imbalance) beyond what one greedy pass can
reach, while preserving the count invariant ``max - min <= 1``.

TPU-native design: instead of one exchange per step (a sequential local
search with a P-sized sort in every iteration), each **round** performs up
to ``max_pairs`` *disjoint* exchanges simultaneously:

1. rank consumers by load (one C-sized argsort — C << P) and pair the
   k-th most-loaded consumer with a partner from the light half, rotating
   the partner permutation every round so a stuck heavy consumer meets
   every possible partner across rounds;
2. for every pair independently, pick the best single-partition **move**
   (heavy → light, lag closest to half the load gap, only while the count
   spread stays <= 1) and the best **swap** — the light side is sorted by
   (pair, quantized lag) once per round, and one vectorized
   ``searchsorted`` finds, for every heavy-side partition p, the
   light-side q whose lag is closest to ``lag_p - delta`` (the best
   counterpart), reduced to the best (p, q) per pair by sort-based
   segmented argmins;
3. apply every strictly-improving exchange at once.  Pairs are disjoint
   (each consumer belongs to at most one), so parallel application is
   race-free, and since any transferred amount d satisfies
   0 < d < load_heavy - load_light, no consumer's load ever exceeds the
   running maximum — the global max is monotone non-increasing.

A round costs two P-sized sorts plus a handful of O(P) elementwise ops and
gathers and retires up to K exchanges, versus the sequential kernel's one
exchange per round; at P=100k / C=1k this is ~3 orders of magnitude more
exchange throughput.  Churn is bounded by ``2 * iters * max_pairs``.

Device-cost discipline (measured on the target TPU, tools/probe_ops.py):
P-sized scatters (8-15 ms) and the sequential ``searchsorted`` method
(18 ms) are banned from the round body — segmented reductions and
permutation handling go through the sort-based primitives in
:mod:`.sortops` (~0.2 ms per P-sized sort), candidate keys are packed
integers (f64 compares are emulated on v5e), and per-row lookups are
packed so each round performs the minimum number of ~2 ms P-sized gathers.
Candidate *selection* works on quantized values, and validity is
enforced by STRICT quantized inequalities that imply the exact ones
(see the safety lemma at ``pack_payload``): quantization can only MISS
boundary candidates, never admit a worsening exchange.  The amounts
actually applied to the load accumulators are exact int64, gathered at
the [K] winners.

The refinement is solver-agnostic: it accepts the (choice, lags) pair in
input order from the greedy kernels or the Sinkhorn rounding.  It
intentionally does NOT reproduce reference semantics — it is the framework's
quality mode (BASELINE config 4); parity solvers remain bit-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .sortops import (
    _cpu_backend,
    bincount_sorted,
    segment_argmin_first,
    segment_sum,
)

_PAIR_BITS = 14  # pair-id field width in the packed per-row combo lookup


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs",
                              "patience")
)
def refine_assignment(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 16,
    max_pairs: int | None = None,
    patience: int = 8,
):
    """Improve an integral assignment by rounds of parallel exchanges.

    Args:
      lags: [P] lag per partition row (non-negative, contract §2.4.6).
      valid: [P] mask; invalid rows must have choice == -1.
      choice: int32[P] consumer index per row (count-balanced).
      num_consumers: static C.
      iters: refinement rounds; each applies up to ``max_pairs`` disjoint,
        strictly-improving exchanges (or no-ops once converged).
      max_pairs: concurrent consumer pairs per round (default C // 2).
        Total churn is bounded by ``2 * iters * max_pairs`` partitions.
      patience: adaptive budget — stop early once this many CONSECUTIVE
        rounds failed to reduce the MAXIMUM consumer load.  The metric is
        max/mean and the mean is invariant (total lag is conserved), so
        only peak reduction counts as progress; exchanges between
        non-peak pairs matter only as enablers of a later peak reduction,
        and ``patience`` rounds of a stuck peak (the heaviest consumer
        meets a different rotated partner each round) make further
        progress unlikely.  Early stop only ever reduces churn, so the
        documented churn bound still holds.

    Returns (choice int32[P], counts int32[C], totals[C]).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    if K >= (1 << _PAIR_BITS) - 1:
        raise ValueError(
            f"max_pairs={K} exceeds the packed pair-id field "
            f"({_PAIR_BITS} bits)"
        )
    big = jnp.iinfo(lags.dtype).max
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangeP = jnp.arange(P, dtype=jnp.int32)

    choice = choice.astype(jnp.int32)
    assigned = valid & (choice >= 0)
    seg0 = jnp.where(assigned, choice, -1)
    totals0 = segment_sum(jnp.where(assigned, lags, 0), seg0, C)
    counts0 = bincount_sorted(seg0, C)
    if C < 2:
        return choice, counts0, totals0

    # Packed integer key for the (pair, lag) composite sort: pair id in the
    # high bits, the lag quantized (right-shifted) into the remaining low
    # bits.  int32 keys whenever the pair id fits comfortably — TPU sorts
    # 32-bit keys natively, vs emulated 64-bit float compares (the previous
    # f64 keys made one refine round cost more than a full greedy solve on
    # v5e).  Quantization is safe: candidates are re-checked EXACTLY before
    # being applied, the key only has to make searchsorted land near the
    # best counterpart.
    pair_bits = max(1, (K - 1).bit_length())
    if pair_bits <= 12:  # lag keeps >= 19 significant bits
        key_dtype, key_bits = jnp.int32, 31
    else:
        key_dtype, key_bits = jnp.int64, 63
    lag_bits = key_bits - pair_bits
    key_big = jnp.iinfo(key_dtype).max
    maxlag = jnp.maximum(jnp.max(jnp.where(assigned, lags, 0)), 1)
    bitlen = 64 - lax.clz(maxlag.astype(jnp.int64))  # bit length of maxlag
    qshift = jnp.maximum(bitlen - lag_bits, 0).astype(jnp.int64)

    def pack_key(pair, lag_like):
        q = jnp.clip(lag_like, 0, None).astype(jnp.int64) >> qshift
        return (pair.astype(key_dtype) << lag_bits) | q.astype(key_dtype)

    # Neighbour payload packing: (quantized lag << SB) | (pair id + 1) in
    # one int64, so each neighbour probe is ONE P-sized gather instead of
    # two (~2 ms each on the target TPU).  Zero means "not a light row"
    # (pair id + 1 >= 1 for real entries).  ``pshift`` extends the key
    # quantization only if lag_bits + SB would overflow 62 bits (only
    # possible on the int64-key path).
    #
    # SAFETY LEMMA (why strict quantized validity implies exact validity,
    # for non-negative a, b, diff and any shift s — there is NO exact
    # recheck downstream, this argument is the whole guarantee):
    #   d_q > 0:       a>>s > b>>s  ⟹  a >= ((b>>s)+1)<<s > b, so d > 0.
    #   d_q < diff_q:  write a = (a>>s)<<s + ra, b = (b>>s)<<s + rb,
    #     diff = (diff>>s)<<s + rd with 0 <= ra, rb, rd < 2^s.  Then
    #     d = a - b = (d_q<<s) + ra - rb < (d_q + 1)<<s <= (diff>>s)<<s
    #     <= diff.  So d < diff.
    # Hence a selected exchange satisfies 0 < d < diff exactly —
    # quantization can only MISS boundary candidates, never admit a
    # worsening exchange, and the monotone non-increasing max is
    # preserved.
    sb = max(1, K.bit_length())
    extra = max(0, (lag_bits + sb) - 62)
    pshift = qshift + extra
    pay_mask = (1 << sb) - 1

    def pack_payload(pair1, lag_like):
        q = jnp.clip(lag_like, 0, None).astype(jnp.int64) >> pshift
        return (q << sb) | pair1.astype(jnp.int64)

    def body(state):
        it, since, choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Rank consumers by load.  Pair the k-th heaviest with a partner
        # from the light half, rotating the partner permutation each round
        # (a bijection on the light half, so pairs stay disjoint).
        order = jnp.argsort(totals).astype(jnp.int32)  # ascending
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        n_light = C - K
        shift = it % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]             # [K]
        heavy = order[C - 1 - jnp.arange(K)]  # [K]
        diff = totals[heavy] - totals[light]  # [K] >= 0

        # Map consumers to pair ids (K = unpaired) and rows to sides via a
        # single packed [C] table -> ONE P-sized gather for both fields.
        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            rank < n_light,
            slot_to_pair[jnp.clip(rank, 0, n_light - 1)],
            C - 1 - rank,
        )
        heavy_side = rank >= C - K
        combo_tab = pair_of | (heavy_side.astype(jnp.int32) << _PAIR_BITS)
        combo = jnp.where(assigned, combo_tab[safe_choice], K)
        k_p = combo & ((1 << _PAIR_BITS) - 1)
        row_heavy = combo >= (1 << _PAIR_BITS)
        on_heavy = assigned & row_heavy & (k_p < K)
        on_light = assigned & ~row_heavy & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = diff[kc]       # the round's second P-sized gather
        delta_p = diff_p >> 1   # diff >= 0, so >>1 == //2
        seg_h = jnp.where(on_heavy, k_p, K)

        # All candidate SELECTION below runs in the quantized (>> pshift)
        # lag domain — one consistent unit for comparing move vs swap
        # errors; the APPLIED amounts are exact (gathered at the [K]
        # winners).  Strict quantized checks guarantee exact validity.
        qlag_row = lags >> pshift
        diff_q = diff_p >> pshift
        delta_q = delta_p >> pshift

        # Candidate 1 — MOVE: heavy-side partition with lag closest to
        # delta; improving iff 0 < lag < diff (exact elementwise check).
        ok_move = on_heavy & (lags > 0) & (lags < diff_p)
        score_move = jnp.where(ok_move, jnp.abs(qlag_row - delta_q), big)
        err_move, p_move = segment_argmin_first(score_move, seg_h, K, P)

        # Candidate 2 — best SWAP: sort light-side rows by (pair,
        # quantized lag) with (payload, row) riding the sort; for each
        # heavy p, searchsorted its ideal counterpart lag_p - delta and
        # examine the two neighbours via their packed payloads.
        keyl = jnp.where(on_light, pack_key(k_p, lags), key_big)
        payload = jnp.where(
            on_light, pack_payload(k_p + 1, lags), 0
        )
        _skey, spayload, sidx = lax.sort(
            (keyl, payload, arangeP), num_keys=1
        )
        tgt = jnp.clip(lags - delta_p, 0, None)
        query = jnp.where(on_heavy, pack_key(k_p, tgt), key_big)
        # method="sort" replaces the sequential binary search with one
        # more bitonic sort — 7x faster on the TPU target; XLA:CPU's
        # vectorized "scan" search beats an extra big sort there.
        method = "scan" if _cpu_backend() else "sort"
        pos = jnp.searchsorted(_skey, query, method=method).astype(jnp.int32)

        def neighbour(nb):
            inb = jnp.clip(nb, 0, P - 1)
            pl = spayload[inb]  # the round's ONE gather per neighbour
            okq = (nb >= 0) & (nb < P) & ((pl & pay_mask) == k_p + 1)
            d_q = qlag_row - (pl >> sb)
            ok = on_heavy & okq & (d_q > 0) & (d_q < diff_q)
            return jnp.where(ok, jnp.abs(d_q - delta_q), big)

        err_a = neighbour(pos - 1)
        err_b = neighbour(pos)
        use_b = err_b < err_a
        err_pq = jnp.where(use_b, err_b, err_a)
        nb_of_p = jnp.where(use_b, pos, pos - 1)
        err_swap, p_swap = segment_argmin_first(err_pq, seg_h, K, P)
        nb_sel = jnp.clip(nb_of_p[jnp.clip(p_swap, 0, P - 1)], 0, P - 1)
        q_swap = sidx[nb_sel]                        # [K]
        lag_q_swap = lags[jnp.clip(q_swap, 0, P - 1)]  # [K], exact lag of q

        # Choose per pair; moves must keep the count spread <= 1.
        move_allowed = (counts[heavy] > counts[light]) & (err_move < big)
        err_move_eff = jnp.where(move_allowed, err_move, big)
        use_move = move_allowed & (err_move_eff <= err_swap)
        use_swap = ~use_move & (err_swap < big)
        do = use_move | use_swap

        p_sel = jnp.where(use_move, p_move, p_swap)
        p_safe = jnp.clip(p_sel, 0, P - 1)
        lag_p_sel = lags[p_safe]  # [K]
        lag_q = jnp.where(use_swap, lag_q_swap, 0)
        d = jnp.where(use_move, lag_p_sel, lag_p_sel - lag_q)
        d = jnp.where(do, d, 0)

        # Apply all exchanges at once (pairs are disjoint -> race-free);
        # K-sized scatters, cost proportional to the K updates.
        upd_p = jnp.where(do, p_sel, P)
        upd_q = jnp.where(use_swap, q_swap, P)
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d).at[light].add(d)
        dc = use_move.astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, 0, since + 1)
        return it + 1, new_since, new_choice, new_totals, new_counts

    def cond(state):
        it, since = state[0], state[1]
        return (it < iters) & (since < patience)

    _, _, choice, totals, counts = lax.while_loop(
        cond,
        body,
        (jnp.int32(0), jnp.int32(0), choice, totals0, counts0),
    )
    return choice, counts, totals
