"""Exchange local-search refinement kernel.

Post-processes any integral, count-balanced assignment to tighten the
north-star metric (max/mean lag imbalance) beyond what one greedy pass can
reach, while preserving the count invariant ``max - min <= 1``.

Each iteration (a ``lax.fori_loop`` step, all vectorized over [P]/[C]):

1. find the most- and least-loaded consumers, jmax / jmin;
2. candidate **swap**: exchange a partition p on jmax with a partition q on
   jmin (counts unchanged).  Ideal transfer is delta = (load_max -
   load_min)/2; q is jmin's lightest partition, p is chosen on jmax with
   lag closest to q.lag + delta;
3. candidate **move**: shift p from jmax to jmin, allowed only when
   count(jmax) > count(jmin) (keeps the count spread <= 1); p closest to
   delta;
4. apply whichever of the applicable candidates reduces the pairwise load
   spread; stop changing anything once no candidate improves (the loop
   body becomes a no-op — convergence is monotone).

The refinement is solver-agnostic: it accepts the (choice, lags) pair in
input order from the greedy kernels or the Sinkhorn rounding.  It
intentionally does NOT reproduce reference semantics — it is the framework's
quality mode (BASELINE config 4), parity solvers remain bit-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("num_consumers", "iters"))
def refine_assignment(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 128,
):
    """Improve an integral assignment by pairwise exchanges.

    Args:
      lags: [P] lag per partition row.
      valid: [P] mask; invalid rows must have choice == -1.
      choice: int32[P] consumer index per row (count-balanced).
      num_consumers: static C.
      iters: local-search steps (each strictly improving or no-op).

    Returns (choice int32[P], counts int32[C], totals[C]).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    big = jnp.iinfo(lags.dtype).max

    safe_choice = jnp.maximum(choice, 0)
    assigned = valid & (choice >= 0)
    totals0 = jnp.zeros((C,), lags.dtype).at[safe_choice].add(
        jnp.where(assigned, lags, 0)
    )
    counts0 = jnp.zeros((C,), jnp.int32).at[safe_choice].add(
        assigned.astype(jnp.int32)
    )

    def body(_, state):
        choice, totals, counts = state
        jmax = jnp.argmax(totals).astype(jnp.int32)
        jmin = jnp.argmin(totals).astype(jnp.int32)

        on_max = (choice == jmax) & valid
        others = valid & (choice >= 0) & (choice != jmax)

        # Per-candidate ideal transfer: q may live on ANY consumer j; moving
        # d from jmax to j improves the pair iff 0 < d < load_max - load_j,
        # ideally d = (load_max - load_j)/2.
        load_of_q = totals[jnp.clip(choice, 0, C - 1)]
        delta_q = (totals[jmax] - load_of_q) // 2

        def closest_on_max(target):
            dist = jnp.where(on_max, jnp.abs(lags - target), big)
            p = jnp.argmin(dist)
            return p, lags[p]

        # Swap candidate: best improving pair (p on jmax, q elsewhere)
        # minimizing |(lag_p - lag_q) - delta_q|.  For each q the best p is
        # a neighbor of (lag_q + delta_q) in jmax's sorted lags — one
        # vectorized searchsorted instead of a PxP cross product.
        sorted_max = jnp.sort(jnp.where(on_max, lags, big))
        targets = jnp.where(others, lags + delta_q, big)
        pos = jnp.searchsorted(sorted_max, targets)
        lo = sorted_max[jnp.clip(pos - 1, 0, P - 1)]
        hi = sorted_max[jnp.clip(pos, 0, P - 1)]

        def pair_err(cand):
            d = cand - lags  # transfer for (cand, q) per q position
            ok = others & (cand != big) & (d > 0) & (d < 2 * delta_q)
            return jnp.where(ok, jnp.abs(d - delta_q), big), d

        err_lo, d_lo = pair_err(lo)
        err_hi, d_hi = pair_err(hi)
        use_hi = err_hi < err_lo
        err = jnp.where(use_hi, err_hi, err_lo)
        d_q = jnp.where(use_hi, d_hi, d_lo)
        cand = jnp.where(use_hi, hi, lo)

        q = jnp.argmin(err).astype(jnp.int32)
        swap_ok = err[q] < big
        d_swap = d_q[q]
        j_swap = jnp.clip(choice[q], 0, C - 1)
        p_s, _ = closest_on_max(cand[q])

        # Move candidate: shift p from jmax to jmin without a counterpart;
        # allowed only while it keeps the count spread <= 1.
        delta_min = (totals[jmax] - totals[jmin]) // 2
        p_m, p_m_lag = closest_on_max(delta_min)
        d_move = p_m_lag
        move_ok = (counts[jmax] > counts[jmin]) & (d_move > 0) & (
            d_move < 2 * delta_min
        )

        # Prefer the candidate with the smaller relative error to its ideal.
        use_swap = swap_ok & (
            ~move_ok | (jnp.abs(d_swap - delta_q[q]) <= jnp.abs(d_move - delta_min))
        )
        use_move = move_ok & ~use_swap

        p = jnp.where(use_swap, p_s, p_m)
        dest = jnp.where(use_swap, j_swap, jmin)
        do = use_swap | use_move

        new_choice = choice
        new_choice = jnp.where(
            do & (jnp.arange(P) == p), dest, new_choice
        )
        new_choice = jnp.where(
            use_swap & (jnp.arange(P) == q), jmax, new_choice
        )
        d = jnp.where(use_swap, d_swap, d_move)
        d = jnp.where(do, d, 0)
        new_totals = totals.at[jmax].add(-d).at[dest].add(d)
        dc = use_move.astype(jnp.int32)
        new_counts = counts.at[jmax].add(-dc).at[dest].add(dc)
        return new_choice, new_totals, new_counts

    choice, totals, counts = lax.fori_loop(
        0, iters, body, (choice, totals0, counts0)
    )
    return choice, counts, totals
