"""Parallel pairwise-exchange refinement kernel.

Post-processes any integral, count-balanced assignment to tighten the
north-star metric (max/mean lag imbalance) beyond what one greedy pass can
reach, while preserving the count invariant ``max - min <= 1``.

TPU-native design: instead of one exchange per step (a sequential local
search with a P-sized sort in every iteration), each **round** performs up
to ``max_pairs`` *disjoint* exchanges simultaneously:

1. rank consumers by load (one C-sized argsort — C << P) and pair the
   k-th most-loaded consumer with a partner from the light half, rotating
   the partner permutation every round so a stuck heavy consumer meets
   every possible partner across rounds;
2. for every pair independently, pick the best single-partition **move**
   (heavy → light, lag closest to half the load gap, only while the count
   spread stays <= 1) and the best **swap** — light rows and heavy-side
   *queries* are co-sorted in ONE packed-key sort (pair id in the high
   bits, quantized lag, a side bit), after which each heavy row's best
   swap counterparts are its nearest light neighbours in sort order,
   found with two cumulative scans (no searchsorted, no second sort);
3. move and swap candidates merge into a single score stream (a tag bit
   under the score keeps ties preferring moves), so ONE sort-based
   segmented argmin picks each pair's exchange; apply every
   strictly-improving exchange at once.  Pairs are disjoint (each
   consumer belongs to at most one), so parallel application is
   race-free, and since any transferred amount d satisfies
   0 < d < load_heavy - load_light, no consumer's load ever exceeds the
   running maximum — the global max is monotone non-increasing.

A round is therefore TWO P-sized sorts (the combined neighbour sort and
the segmented argmin) plus cumulative scans, elementwise ops, and a few
gathers — versus the previous generation's five sort passes
(light-key sort, a 2P sort-based searchsorted, and two segmented
argmins); fetch-synchronized probes on the target TPU
(tools/probe_round5c.py — ``block_until_ready`` is NOT a valid clock on
this platform) put a P=131072 sort at ~0.4 ms, making op count, not
element count, the budget.  Churn is bounded by ``2 * iters * max_pairs``.

Candidate *selection* works on quantized values; validity is enforced by
STRICT quantized inequalities that imply the exact ones (see the safety
lemma below): quantization can only MISS boundary candidates, never admit
a worsening exchange.  With the single 48-bit value field the quantization
shift is 0 (exact selection) for any lag below 2^48.  The amounts actually
applied to the load accumulators are exact int64, gathered at the [K]
winners.

SAFETY LEMMA (why strict quantized validity implies exact validity, for
non-negative a, b, diff and any shift s — there is NO exact recheck
downstream for swaps, this argument is the whole guarantee):
  d_q > 0:       a>>s > b>>s  ⟹  a >= ((b>>s)+1)<<s > b, so d > 0.
  d_q < diff_q:  write a = (a>>s)<<s + ra, b = (b>>s)<<s + rb,
    diff = (diff>>s)<<s + rd with 0 <= ra, rb, rd < 2^s.  Then
    d = a - b = (d_q<<s) + ra - rb < (d_q + 1)<<s <= (diff>>s)<<s
    <= diff.  So d < diff.
Hence a selected swap satisfies 0 < d < diff exactly — the monotone
non-increasing max is preserved.  (Moves check 0 < lag < diff on the
exact lag directly.)

The refinement is solver-agnostic: it accepts the (choice, lags) pair in
input order from the greedy kernels or the Sinkhorn rounding.  It
intentionally does NOT reproduce reference semantics — it is the framework's
quality mode (BASELINE config 4); parity solvers remain bit-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .sortops import bincount_sorted, segment_argmin_first, segment_sum

_PAIR_BITS = 14  # pair-id field width in the packed keys
_VBITS = 63 - _PAIR_BITS - 1  # quantized-lag field width (48)
# Score sentinel (fits (x << 1) | 1 in int64).  A plain Python int on
# purpose: a module-level ``jnp.int64(...)`` would be created EAGERLY at
# import time, and if the importer has not enabled x64 yet it silently
# truncates to int32 garbage (observed: every exchange candidate scored
# "valid" 0 and the kernel became a no-op).  As a Python int it converts
# at trace time, after the entry points' ensure_x64().
_SBIG_INT = 1 << 60


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs",
                              "patience")
)
def refine_assignment(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 16,
    max_pairs: int | None = None,
    patience: int = 8,
):
    """Improve an integral assignment by rounds of parallel exchanges.

    Args:
      lags: [P] lag per partition row (non-negative, contract §2.4.6).
      valid: [P] mask; invalid rows must have choice == -1.
      choice: int32[P] consumer index per row (count-balanced).
      num_consumers: static C.
      iters: refinement rounds; each applies up to ``max_pairs`` disjoint,
        strictly-improving exchanges (or no-ops once converged).
      max_pairs: concurrent consumer pairs per round (default C // 2).
        Total churn is bounded by ``2 * iters * max_pairs`` partitions.
      patience: adaptive budget — stop early once this many CONSECUTIVE
        rounds failed to reduce the MAXIMUM consumer load.  The metric is
        max/mean and the mean is invariant (total lag is conserved), so
        only peak reduction counts as progress; exchanges between
        non-peak pairs matter only as enablers of a later peak reduction,
        and ``patience`` rounds of a stuck peak (the heaviest consumer
        meets a different rotated partner each round) make further
        progress unlikely.  Early stop only ever reduces churn, so the
        documented churn bound still holds.

    Returns (choice int32[P], counts int32[C], totals[C]).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    if K >= (1 << _PAIR_BITS) - 1:
        raise ValueError(
            f"max_pairs={K} exceeds the packed pair-id field "
            f"({_PAIR_BITS} bits)"
        )
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangeP = jnp.arange(P, dtype=jnp.int32)
    key_big = jnp.iinfo(jnp.int64).max
    vmask = (jnp.int64(1) << _VBITS) - 1
    sbig = jnp.asarray(_SBIG_INT, jnp.int64)

    choice = choice.astype(jnp.int32)
    assigned = valid & (choice >= 0)
    seg0 = jnp.where(assigned, choice, -1)
    totals0 = segment_sum(jnp.where(assigned, lags, 0), seg0, C)
    counts0 = bincount_sorted(seg0, C)
    if C < 2:
        return choice, counts0, totals0

    # Quantization shift: the 48-bit value field holds any lag below 2^48
    # exactly (shift 0); larger lags shift just enough to fit.  Selection
    # compares live in the shifted domain; strictness makes them sound
    # (safety lemma, module docstring).
    maxlag = jnp.maximum(jnp.max(jnp.where(assigned, lags, 0)), 1)
    bitlen = 64 - lax.clz(maxlag.astype(jnp.int64))
    pshift = jnp.maximum(bitlen - _VBITS, 0).astype(jnp.int64)

    def body(state):
        it, since, choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Rank consumers by load.  Pair the k-th heaviest with a partner
        # from the light half, rotating the partner permutation each round
        # (a bijection on the light half, so pairs stay disjoint).
        order = jnp.argsort(totals).astype(jnp.int32)  # ascending
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        n_light = C - K
        shift = it % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]             # [K]
        heavy = order[C - 1 - jnp.arange(K)]  # [K]
        diff = totals[heavy] - totals[light]  # [K] >= 0

        # Per-consumer combo table -> ONE P-sized gather for pair id,
        # side, and the move-permission bit (moves must keep the count
        # spread <= 1, a per-pair property known before selection).
        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            rank < n_light,
            slot_to_pair[jnp.clip(rank, 0, n_light - 1)],
            C - 1 - rank,
        )
        heavy_side = rank >= C - K
        move_ok_pair = counts[heavy] > counts[light]  # [K]
        move_ok_of = jnp.where(
            heavy_side,
            jnp.pad(move_ok_pair, (0, 1))[jnp.clip(pair_of, 0, K)],
            False,
        )
        combo_tab = (
            pair_of
            | (heavy_side.astype(jnp.int32) << _PAIR_BITS)
            | (move_ok_of.astype(jnp.int32) << (_PAIR_BITS + 1))
        )
        combo = jnp.where(assigned, combo_tab[safe_choice], -1)
        k_p = combo & ((1 << _PAIR_BITS) - 1)
        row_heavy = (combo >> _PAIR_BITS) & 1
        row_move_ok = (combo >> (_PAIR_BITS + 1)) & 1
        participates = (combo >= 0) & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = jnp.where(participates, diff[kc], 0)
        delta_p = diff_p >> 1  # diff >= 0, so >>1 == //2

        # THE round sort: light rows keyed by their own quantized lag,
        # heavy rows keyed by their ideal counterpart lag (lag - delta),
        # pair id in the high bits, side bit last (equal-valued lights
        # sort before the heavy query).  After this one sort each heavy
        # row's best swap counterparts are its nearest light neighbours.
        qself = lags >> pshift
        tgt = jnp.clip(lags - delta_p, 0, None) >> pshift
        qval = jnp.where(row_heavy == 1, tgt, qself)
        key = jnp.where(
            participates,
            (k_p.astype(jnp.int64) << (_VBITS + 1))
            | (jnp.clip(qval, 0, vmask) << 1)
            | row_heavy.astype(jnp.int64),
            key_big,
        )
        skey, slag, srow, smove_ok = lax.sort(
            (key, lags, arangeP, row_move_ok), num_keys=1
        )

        part_s = skey < key_big
        pair_s = (skey >> (_VBITS + 1)).astype(jnp.int32)
        heavy_s = part_s & ((skey & 1) == 1)
        light_s = part_s & ((skey & 1) == 0)
        qlag_s = slag >> pshift
        diff_s = jnp.where(
            heavy_s, diff[jnp.clip(pair_s, 0, K - 1)], 0
        )
        delta_q_s = (diff_s >> 1) >> pshift
        diff_q_s = diff_s >> pshift

        # Nearest light neighbours via cumulative scans (replaces the
        # previous sort-based searchsorted): prev = last light at or
        # below, nxt = first light above.  A neighbour from another pair
        # fails the pair check below, exactly like a searchsorted landing
        # at a pair boundary did.
        prev_l = lax.cummax(jnp.where(light_s, arangeP, -1))
        nxt_l = lax.cummin(
            jnp.where(light_s, arangeP, P), reverse=True
        )

        def neighbour(nb):
            inb = jnp.clip(nb, 0, P - 1)
            nkey = skey[inb]  # one P-sized gather per neighbour
            okq = (
                (nb >= 0) & (nb < P)
                & ((nkey & 1) == 0)
                & ((nkey >> (_VBITS + 1)).astype(jnp.int32) == pair_s)
            )
            d_q = qlag_s - ((nkey >> 1) & vmask)
            ok = heavy_s & okq & (d_q > 0) & (d_q < diff_q_s)
            return jnp.where(ok, jnp.abs(d_q - delta_q_s), sbig)

        err_a = neighbour(prev_l)
        err_b = neighbour(nxt_l)
        use_b = err_b < err_a
        err_swap = jnp.where(use_b, err_b, err_a)
        nb_sel = jnp.where(use_b, nxt_l, prev_l)

        # Move candidate (exact validity on the resident lag) merged with
        # the swap via a tag bit under the score: ties prefer the move.
        ok_move = (
            heavy_s & (smove_ok == 1) & (slag > 0) & (slag < diff_s)
        )
        score_move = jnp.where(
            ok_move, jnp.abs(qlag_s - delta_q_s), sbig
        )
        combined = jnp.where(
            score_move <= err_swap,
            score_move << 1,
            (err_swap << 1) | 1,
        )
        seg_h = jnp.where(heavy_s, pair_s, K)
        minv, widx = segment_argmin_first(combined, seg_h, K, P)

        # Decode the [K] winners; all remaining work is K-sized.
        do = minv < (sbig << 1)
        is_swap = (minv & 1) == 1
        wclip = jnp.clip(widx, 0, P - 1)
        p_sel = srow[wclip]
        lag_p = slag[wclip]
        nb_k = jnp.clip(nb_sel[wclip], 0, P - 1)
        q_sel = srow[nb_k]
        lag_q = slag[nb_k]
        use_swap = do & is_swap
        d = jnp.where(use_swap, lag_p - lag_q, lag_p)
        d = jnp.where(do, d, 0)

        # Apply all exchanges at once (pairs are disjoint -> race-free);
        # K-sized scatters, cost proportional to the K updates.
        upd_p = jnp.where(do, p_sel, P)
        upd_q = jnp.where(use_swap, q_sel, P)
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d).at[light].add(d)
        dc = (do & ~is_swap).astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        peak_dropped = jnp.max(new_totals) < jnp.max(totals)
        new_since = jnp.where(peak_dropped, 0, since + 1)
        return it + 1, new_since, new_choice, new_totals, new_counts

    def cond(state):
        it, since = state[0], state[1]
        return (it < iters) & (since < patience)

    _, _, choice, totals, counts = lax.while_loop(
        cond,
        body,
        (jnp.int32(0), jnp.int32(0), choice, totals0, counts0),
    )
    return choice, counts, totals
