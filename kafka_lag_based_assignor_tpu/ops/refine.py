"""Parallel pairwise-exchange refinement kernel.

Post-processes any integral, count-balanced assignment to tighten the
north-star metric (max/mean lag imbalance) beyond what one greedy pass can
reach, while preserving the count invariant ``max - min <= 1``.

TPU-native design: instead of one exchange per step (a sequential local
search with a P-sized sort in every iteration), each **round** performs up
to ``max_pairs`` *disjoint* exchanges simultaneously:

1. rank consumers by load (one C-sized argsort — C << P) and pair the
   k-th most-loaded consumer with a partner from the light half, rotating
   the partner permutation every round so a stuck heavy consumer meets
   every possible partner across rounds;
2. for every pair independently, pick the best single-partition **move**
   (heavy → light, lag closest to half the load gap, only while the count
   spread stays <= 1) and the best **swap** — the light side is sorted by
   (pair, lag) once per round, and one vectorized ``searchsorted`` finds,
   for every heavy-side partition p, the light-side q whose lag is
   closest to ``lag_p - delta`` (the exact best counterpart), reduced to
   the best (p, q) per pair by O(P) segment-argmin scatter ops;
3. apply every strictly-improving exchange at once.  Pairs are disjoint
   (each consumer belongs to at most one), so parallel application is
   race-free, and since any transferred amount d satisfies
   0 < d < load_heavy - load_light, no consumer's load ever exceeds the
   running maximum — the global max is monotone non-increasing.

A round costs one P-sized sort plus a handful of O(P) gathers/scatters
and retires up to K exchanges, versus the sequential kernel's one
exchange per round; at P=100k / C=1k this is ~3 orders of magnitude more
exchange throughput.  Churn is bounded by ``2 * iters * max_pairs``.

The refinement is solver-agnostic: it accepts the (choice, lags) pair in
input order from the greedy kernels or the Sinkhorn rounding.  It
intentionally does NOT reproduce reference semantics — it is the framework's
quality mode (BASELINE config 4); parity solvers remain bit-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _segment_argmin(score, seg, num_segments, P):
    """Deterministic per-segment argmin: returns (min value, first index
    attaining it) per segment.  ``seg`` entries equal to ``num_segments``
    are parked in a discard slot.  Two O(P) scatter-mins."""
    big = jnp.iinfo(score.dtype).max
    minv = jnp.full((num_segments + 1,), big, score.dtype).at[seg].min(score)
    hit = (score == minv[seg]) & (seg < num_segments)
    idx_cand = jnp.where(hit, jnp.arange(P, dtype=jnp.int32), P)
    idx = jnp.full((num_segments + 1,), P, jnp.int32).at[seg].min(idx_cand)
    return minv[:num_segments], idx[:num_segments]


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "max_pairs")
)
def refine_assignment(
    lags: jax.Array,
    valid: jax.Array,
    choice: jax.Array,
    num_consumers: int,
    iters: int = 16,
    max_pairs: int | None = None,
):
    """Improve an integral assignment by rounds of parallel exchanges.

    Args:
      lags: [P] lag per partition row.
      valid: [P] mask; invalid rows must have choice == -1.
      choice: int32[P] consumer index per row (count-balanced).
      num_consumers: static C.
      iters: refinement rounds; each applies up to ``max_pairs`` disjoint,
        strictly-improving exchanges (or no-ops once converged).
      max_pairs: concurrent consumer pairs per round (default C // 2).
        Total churn is bounded by ``2 * iters * max_pairs`` partitions.

    Returns (choice int32[P], counts int32[C], totals[C]).
    """
    C = int(num_consumers)
    P = lags.shape[0]
    K = max(1, min(C // 2, max_pairs if max_pairs is not None else C // 2))
    big = jnp.iinfo(lags.dtype).max
    arangeC = jnp.arange(C, dtype=jnp.int32)

    choice = choice.astype(jnp.int32)
    safe0 = jnp.clip(choice, 0, C - 1)
    assigned = valid & (choice >= 0)
    totals0 = jnp.zeros((C,), lags.dtype).at[safe0].add(
        jnp.where(assigned, lags, 0)
    )
    counts0 = jnp.zeros((C,), jnp.int32).at[safe0].add(
        assigned.astype(jnp.int32)
    )
    if C < 2:
        return choice, counts0, totals0

    # Float key scale for the (pair, lag) composite sort.  Approximate
    # (52-bit mantissa vs 63-bit lags) is fine: candidates are re-checked
    # exactly before being applied.
    scale = (jnp.max(jnp.where(assigned, lags, 0)) + 1).astype(jnp.float64)

    def body(it, state):
        choice, totals, counts = state
        safe_choice = jnp.clip(choice, 0, C - 1)

        # Rank consumers by load.  Pair the k-th heaviest with a partner
        # from the light half, rotating the partner permutation each round
        # (a bijection on the light half, so pairs stay disjoint).
        order = jnp.argsort(totals).astype(jnp.int32)  # ascending
        rank = jnp.zeros((C,), jnp.int32).at[order].set(arangeC)
        n_light = C - K
        shift = jnp.asarray(it, jnp.int32) % jnp.int32(n_light)
        light_slot = (jnp.arange(K, dtype=jnp.int32) + shift) % n_light
        light = order[light_slot]             # [K]
        heavy = order[C - 1 - jnp.arange(K)]  # [K]
        diff = totals[heavy] - totals[light]  # [K] >= 0
        delta = diff // 2

        # Map consumers to pair ids (K = unpaired) and partitions to sides.
        r = rank
        slot_to_pair = (
            jnp.full((n_light,), K, jnp.int32)
            .at[light_slot]
            .set(jnp.arange(K, dtype=jnp.int32))
        )
        pair_of = jnp.where(
            r < n_light, slot_to_pair[jnp.clip(r, 0, n_light - 1)], C - 1 - r
        )
        heavy_side = r >= C - K
        k_p = jnp.where(assigned, pair_of[safe_choice], K)
        on_heavy = assigned & heavy_side[safe_choice] & (k_p < K)
        on_light = assigned & ~heavy_side[safe_choice] & (k_p < K)
        kc = jnp.clip(k_p, 0, K - 1)
        diff_p = diff[kc]
        delta_p = delta[kc]
        seg_h = jnp.where(on_heavy, k_p, K)

        # Candidate 1 — MOVE: heavy-side partition with lag closest to
        # delta; improving iff 0 < lag < diff.
        ok_move = on_heavy & (lags > 0) & (lags < diff_p)
        score_move = jnp.where(ok_move, jnp.abs(lags - delta_p), big)
        err_move, p_move = _segment_argmin(score_move, seg_h, K, P)

        # Candidate 2 — exact best SWAP: sort light-side partitions by
        # (pair, lag); for each heavy p, searchsorted its ideal
        # counterpart lag_p - delta and examine the two neighbours.
        keyl = jnp.where(
            on_light,
            k_p.astype(jnp.float64) + lags.astype(jnp.float64) / scale,
            jnp.inf,
        )
        perm = jnp.argsort(keyl).astype(jnp.int32)
        skey = keyl[perm]
        tgt = jnp.clip(lags - delta_p, 0, None).astype(jnp.float64) / scale
        query = jnp.where(on_heavy, k_p.astype(jnp.float64) + tgt, jnp.inf)
        pos = jnp.searchsorted(skey, query).astype(jnp.int32)

        def neighbour(nb):
            inb = jnp.clip(nb, 0, P - 1)
            qi = perm[inb]
            okq = (nb >= 0) & (nb < P) & on_light[qi] & (k_p[qi] == k_p)
            d = lags - lags[qi]
            ok = on_heavy & okq & (d > 0) & (d < diff_p)
            return jnp.where(ok, jnp.abs(d - delta_p), big), qi

        err_a, q_a = neighbour(pos - 1)
        err_b, q_b = neighbour(pos)
        use_b = err_b < err_a
        err_pq = jnp.where(use_b, err_b, err_a)
        q_of_p = jnp.where(use_b, q_b, q_a)
        err_swap, p_swap = _segment_argmin(err_pq, seg_h, K, P)
        q_swap = q_of_p[jnp.clip(p_swap, 0, P - 1)]

        # Choose per pair; moves must keep the count spread <= 1.
        move_allowed = (counts[heavy] > counts[light]) & (err_move < big)
        err_move_eff = jnp.where(move_allowed, err_move, big)
        use_move = move_allowed & (err_move_eff <= err_swap)
        use_swap = ~use_move & (err_swap < big)
        do = use_move | use_swap

        p_sel = jnp.where(use_move, p_move, p_swap)
        p_safe = jnp.clip(p_sel, 0, P - 1)
        lag_q = jnp.where(use_swap, lags[jnp.clip(q_swap, 0, P - 1)], 0)
        d = jnp.where(use_move, lags[p_safe], lags[p_safe] - lag_q)
        d = jnp.where(do, d, 0)

        # Apply all exchanges at once (pairs are disjoint -> race-free).
        upd_p = jnp.where(do, p_sel, P)
        upd_q = jnp.where(use_swap, q_swap, P)
        new_choice = choice.at[upd_p].set(light, mode="drop")
        new_choice = new_choice.at[upd_q].set(heavy, mode="drop")
        new_totals = totals.at[heavy].add(-d).at[light].add(d)
        dc = use_move.astype(jnp.int32)
        new_counts = counts.at[heavy].add(-dc).at[light].add(dc)
        return new_choice, new_totals, new_counts

    choice, totals, counts = lax.fori_loop(
        0, iters, body, (choice, totals0, counts0)
    )
    return choice, counts, totals
