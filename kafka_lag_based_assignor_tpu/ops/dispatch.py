"""Host<->device dispatch: map-based API in, kernels on device, maps out.

Converts the reference core's signature —
``(Map<topic, List<TopicPartitionLag>>, Map<member, List<topic>>) ->
Map<member, List<TopicPartition>>`` (LagBasedPartitionAssignor.java:166-188)
— into columnar tensors, runs an assignment kernel, and rebuilds per-member
partition lists in the reference's append order (processing order: lag
descending, partition id ascending).

Member-rank convention: per topic, the subscribed members are sorted
lexicographically and the kernel sees dense indices; index order == id
order, so the kernel's integer tie-break reproduces the reference's string
compare (:259) exactly.

Shapes are padded to buckets (next power of two) so repeated rebalances at
similar scale reuse the jit cache instead of recompiling (SURVEY §7:
host/device round-trip budget — avoid recompiles via static padded shapes).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Mapping, Sequence

import numpy as np

import jax

from ..models.greedy import consumers_per_topic
from ..types import AssignmentMap, TopicPartition, TopicPartitionLag
from .rounds_kernel import assign_topic_rounds
from .scan_kernel import assign_topic_scan

KernelFn = Callable[..., tuple]

_KERNELS: Dict[str, KernelFn] = {
    "rounds": assign_topic_rounds,
    "scan": assign_topic_scan,
}


def ensure_x64() -> None:
    """int64 lags (Kafka offsets are Java longs) require JAX x64 mode."""
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


def pad_bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n, so shape-polymorphic workloads hit a
    bounded number of jit cache entries."""
    b = minimum
    while b < n:
        b *= 2
    return b


def _lag_dtype():
    ensure_x64()
    return np.int64


def assign_topic_device(
    topic: str,
    consumers: Sequence[str],
    partition_lags: Sequence[TopicPartitionLag],
    kernel: str = "rounds",
) -> Dict[str, List[TopicPartition]]:
    """Run one topic's assignment on device; returns member -> partitions
    in reference append order.

    Duplicate member ids in ``consumers`` are deduplicated, matching the
    reference where per-consumer accumulators are maps keyed by member id
    (:216-225) even though consumersPerTopic can append duplicates.
    """
    ranked = sorted(set(consumers))
    C = len(ranked)
    P = len(partition_lags)
    if C == 0 or P == 0:
        return {m: [] for m in ranked}

    P_pad = pad_bucket(P)
    lags = np.zeros((P_pad,), dtype=_lag_dtype())
    pids = np.zeros((P_pad,), dtype=np.int32)
    valid = np.zeros((P_pad,), dtype=bool)
    lags[:P] = np.fromiter((r.lag for r in partition_lags), np.int64, count=P)
    pids[:P] = np.fromiter((r.partition for r in partition_lags), np.int32, count=P)
    valid[:P] = True

    kernel_fn = _KERNELS[kernel]
    choice, _, _ = kernel_fn(lags, pids, valid, num_consumers=C)
    choice = np.asarray(choice)[:P]

    # Rebuild lists in processing order (lag desc, pid asc) — the order the
    # reference appends in (:237-264).  Stable argsort over the choice array
    # (itself traversed in processing order) groups rows per consumer while
    # preserving that order, without a Python-level loop over P.
    order = np.lexsort((pids[:P], -lags[:P]))
    sorted_choice = choice[order]
    sorted_pids = pids[:P][order]
    grouped = np.argsort(sorted_choice, kind="stable")
    counts = np.bincount(sorted_choice[sorted_choice >= 0], minlength=C)
    result: Dict[str, List[TopicPartition]] = {}
    pos = int((sorted_choice < 0).sum())  # padding rows group first (-1)
    for c, member in enumerate(ranked):
        rows = grouped[pos : pos + int(counts[c])]
        result[member] = [TopicPartition(topic, int(sorted_pids[i])) for i in rows]
        pos += int(counts[c])
    return result


def assign_device(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    kernel: str = "rounds",
) -> AssignmentMap:
    """Device-backed equivalent of the reference's static core
    (:166-188) — full parity including empty members and missing-lag topics.

    Topics are dispatched one kernel call per topic; topics whose subscriber
    sets coincide share jit cache entries via the rank convention and shape
    bucketing.  (Batched vmap execution across topics lives in
    :mod:`.batched`.)
    """
    assignment: AssignmentMap = {m: [] for m in subscriptions}
    by_topic = consumers_per_topic(subscriptions)
    for topic in sorted(by_topic):
        part = assign_topic_device(
            topic,
            by_topic[topic],
            partition_lag_per_topic.get(topic, ()),
            kernel=kernel,
        )
        for member, tps in part.items():
            assignment[member].extend(tps)
    return assignment
