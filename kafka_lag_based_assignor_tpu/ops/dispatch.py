"""Host<->device dispatch: map-based API in, batched kernels on device, maps out.

Converts the reference core's signature —
``(Map<topic, List<TopicPartitionLag>>, Map<member, List<topic>>) ->
Map<member, List<TopicPartition>>`` (LagBasedPartitionAssignor.java:166-188)
— into packed topic groups (:mod:`.packing`), runs one batched kernel launch
per group (:mod:`.batched`), and rebuilds per-member partition lists in the
reference's append order: topics in sorted order, partitions within a topic
in processing order (lag descending, partition id ascending, :228-235).

Member-rank convention: per group, subscribed members sorted
lexicographically map to dense kernel indices, so the kernel's integer
tie-break reproduces the reference's member-id string compare (:259).

Backend selection (multi-device): :func:`sharded_solve_manager` is the
ONE place a huge single solve is routed to the P-axis-sharded backend
(:mod:`..sharded.solve`) — the active mesh manager
(``tpu.assignor.mesh.devices``), its health, and the
single-device-wins row floor all gate here.  Single-device remains the
default AND the degradation target: a missing/degraded mesh answers
None and callers run the unchanged single-device path; a sharded
dispatch that faults (``mesh.collective``) degrades the manager and
falls back inside the same request budget.

Quality-mode selection (``tpu.assignor.quality.mode``):
:func:`resolve_quality_mode` is the ONE place a quality solve is
routed between the dense Sinkhorn path (:mod:`..models.sinkhorn`) and
the linear-space O(P + C) mirror-prox path (:mod:`.linear_ot`) —
``sinkhorn`` / ``linear`` pin a mode process-wide, ``auto`` (default)
picks linear at row counts where the dense [U, C] streams stop
fitting, or whenever the active mesh elects the P-sharded backend for
the shape (the two compose: the linear duals shard over the same
mesh).  ``assign_topic_sinkhorn`` consults it on entry, so every
existing caller — and the streaming cold path — picks the mode up
without API change.
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

import jax

from ..models.greedy import consumers_per_topic
from ..types import AssignmentMap, TopicPartition, TopicPartitionLag
from ..utils import faults
from .batched import (
    assign_batched_rounds,
    assign_batched_scan,
    totals_rank_bits_for,
)
from .packing import TopicGroup, build_groups, pad_bucket
from .rounds_kernel import assign_global_rounds
from .scan_kernel import pack_shift_for

LOGGER = logging.getLogger(__name__)

# Last pack_shift seen per (kernel, T, P, C) call signature: pack_shift is a
# STATIC jit argument derived from the inputs' value ranges, so a lag
# magnitude drifting across the packing bound silently triggers a fresh XLA
# compile (tens of seconds on a remote-compile transport).  The flip itself
# is correct — both shift values produce identical assignments — but it
# must be observable, and deployments that can see both ranges should warm
# both variants (warmup.warmup's stream job compiles the narrow- and
# wide-lag variants for exactly this reason).
_LAST_PACK_SHIFT: Dict[Tuple, int] = {}


def observe_pack_shift(key: Tuple, shift) -> None:
    """INFO-log changes in value-derived STATIC kernel args per call
    signature (a recompile signal).  ``shift`` may be a plain pack shift
    or a tuple of static args (e.g. ``(pack_shift, totals_rank_bits)``) —
    logged structurally, any change means a fresh executable.  Every
    observed change also bumps the process-wide drift counter
    (utils/observability.static_drift_count) so benches and deployments
    can assert the steady state is drift-free without log scraping."""
    prev = _LAST_PACK_SHIFT.get(key)
    if prev is not None and prev != shift:
        from ..utils.observability import note_static_drift

        note_static_drift()
        LOGGER.info(
            "static kernel args for %s changed %s -> %s (input value "
            "ranges drifted): this solve compiles a fresh executable "
            "unless the variant was warmed (see warmup.warmup)",
            key, prev, shift,
        )
    _LAST_PACK_SHIFT[key] = shift

# "global" returns a single [C] totals vector (cross-topic) instead of
# [T, C]; choice/counts contracts are identical across all three.
_BATCHED_KERNELS = {
    "rounds": assign_batched_rounds,
    "scan": assign_batched_scan,
    "global": assign_global_rounds,
}


def ensure_x64() -> None:
    """int64 lags (Kafka offsets are Java longs) require JAX x64 mode."""
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


#: Valid ``tpu.assignor.quality.mode`` values (mirrored in
#: utils/config so a typo fails at configure() time).
QUALITY_MODES = ("sinkhorn", "linear", "auto")

#: "auto" routes the quality solve to the linear-space mode at or
#: above this many partition rows: past it the dense path's [U, C]
#: streamed working set (U capped at models/sinkhorn._DEDUP_CAP) stops
#: paying for its dedup pre-pass, and the O(P + C) path is both
#: smaller and sharding-composable.  Below it the dense Sinkhorn path
#: keeps its measured latency edge.
LINEAR_AUTO_MIN_ROWS = 32768

# Process-wide quality-plane knobs (the faults._ACTIVE pattern: one
# dict load on the hot path; service start() installs the configured
# values, tests scope overrides via quality_scope).
_QUALITY = {"mode": "auto", "tile": 1024}
_QUALITY_LOCK = threading.Lock()


def normalize_quality_mode(mode) -> str:
    m = str(mode)
    if m not in QUALITY_MODES:
        raise ValueError(
            f"quality mode {mode!r} invalid; choose one of {QUALITY_MODES}"
        )
    return m


def set_quality_mode(mode) -> str:
    """Install the process-wide quality mode (service start(), tests)."""
    m = normalize_quality_mode(mode)
    with _QUALITY_LOCK:
        _QUALITY["mode"] = m
    return m


def quality_mode() -> str:
    return _QUALITY["mode"]


def set_quality_tile(tile) -> int:
    """Install the process-wide linear-mode tile size (pow2 rows per
    streamed tile — the ``tpu.assignor.quality.tile`` knob)."""
    from .linear_ot import validate_tile

    t = validate_tile(tile)
    with _QUALITY_LOCK:
        _QUALITY["tile"] = t
    return t


def quality_tile() -> int:
    return _QUALITY["tile"]


# How the process-wide tile was last chosen ("default" until boot
# autotune runs; then "autotuned" or "cpu-default") plus the memory
# figure the choice was derived from — quality_status surfaces it.
_TILE_SOURCE = {"source": "default", "memory_bytes": None}


def autotune_quality_tile(memory_stats=None) -> int:
    """Boot-time autotune of ``tpu.assignor.quality.tile`` from the
    device's ``memory_stats`` instead of the static default (called
    from :func:`...warmup.warmup` before the quality jobs compile, so
    the chosen geometry is the one that gets warmed).

    Sizing rule: the linear-OT tile scan keeps ~3 live (tile, C) f32
    blocks per step (:func:`.linear_ot._peak_bytes_estimate`), so the
    tile is the largest pow2 with ``3 * tile * 1024 * 4`` (C sized at
    the north-star 1000-consumer lane pad) under 1/8th of the
    device's free memory — conservative, because the [P2] row vectors
    and the refine buffers share the same HBM.  On CPU (no
    ``memory_stats``) the static default stays: tier-1 runs must keep
    one deterministic geometry.  The choice is logged through the
    metrics registry (``klba_quality_tile_autotuned{source}``)."""
    from ..utils import metrics

    if memory_stats is None:
        try:
            dev = jax.devices()[0]
            memory_stats = (
                dev.memory_stats() if dev.platform != "cpu" else None
            )
        except Exception:  # backends without memory introspection
            LOGGER.debug(
                "device memory_stats unavailable; keeping the static "
                "quality tile", exc_info=True,
            )
            memory_stats = None
    if not memory_stats:
        _TILE_SOURCE.update(source="cpu-default", memory_bytes=None)
        metrics.REGISTRY.gauge(
            "klba_quality_tile_autotuned", {"source": "cpu-default"}
        ).set(quality_tile())
        return quality_tile()
    free = int(
        memory_stats.get("bytes_limit", 0)
        - memory_stats.get("bytes_in_use", 0)
    )
    budget = max(free // 8, 1)
    tile = 8
    while tile * 2 <= 65536 and 3 * (tile * 2) * 1024 * 4 <= budget:
        tile *= 2
    chosen = set_quality_tile(tile)
    _TILE_SOURCE.update(source="autotuned", memory_bytes=free)
    metrics.REGISTRY.gauge(
        "klba_quality_tile_autotuned", {"source": "autotuned"}
    ).set(chosen)
    LOGGER.info(
        "quality tile autotuned to %d rows (device free memory %d "
        "bytes)", chosen, free,
    )
    return chosen


@contextmanager
def quality_scope(mode, tile: Optional[int] = None):
    """Scope a quality mode (and optionally a tile size) to a block —
    tests and the per-mode warm-up jobs force one mode regardless of
    the process-wide setting.  The previous knobs are restored even
    when a setter rejects its value (an invalid tile must not leave
    the mode permanently rerouted)."""
    with _QUALITY_LOCK:
        prev = dict(_QUALITY)
    try:
        set_quality_mode(mode)
        if tile is not None:
            set_quality_tile(tile)
        yield
    finally:
        with _QUALITY_LOCK:
            _QUALITY.update(prev)


def resolve_quality_mode(num_rows: int, num_consumers: int) -> str:
    """THE quality-mode router (module docstring): the mode one
    P-rows-by-C-consumers quality solve should run.  Pinned modes win;
    "auto" picks linear at scale (the row floor).  Callers that can
    actually SHARD the solve — the streaming cold hook, which already
    holds an electing mesh — additionally prefer linear under "auto"
    at any size (the linear duals are the only quality iteration that
    composes with the mesh); a plain single-device quality solve below
    the floor keeps the dense path's measured latency edge."""
    mode = _QUALITY["mode"]
    if mode != "auto":
        return mode
    if int(num_consumers) < 2:
        return "sinkhorn"
    if int(num_rows) >= LINEAR_AUTO_MIN_ROWS:
        return "linear"
    return "sinkhorn"


def quality_status() -> Dict:
    """The service ``stats.quality`` section (and dump_metrics
    --summary's quality rows): mode/tile knobs (plus how the tile was
    chosen), the last linear solve's tile count and peak-memory
    estimate, and the kernel-plane gate verdicts."""
    from .linear_ot import last_solve_info
    from .linear_ot_pallas import linear_pallas_available

    return {
        "mode": quality_mode(),
        "tile": quality_tile(),
        "tile_source": dict(_TILE_SOURCE),
        "auto_min_rows": LINEAR_AUTO_MIN_ROWS,
        "last_linear_solve": last_solve_info(),
        "kernel": dict(
            duals=linear_pallas_available(kind="duals"),
            digest=linear_pallas_available(kind="digest"),
        ),
    }


def sharded_solve_manager(num_rows: int, num_consumers: int):
    """Backend selection for one P-sized solve: the active
    :class:`..sharded.mesh.MeshManager` when the P-axis-sharded backend
    should serve this shape, else None (single-device default).  One
    global load + a couple of int compares on the unconfigured path —
    safe on the cold-solve boundary."""
    from ..sharded import mesh as mesh_mod

    mgr = mesh_mod.active_manager()
    if mgr is None or int(num_consumers) < 2:
        return None
    return mgr if mgr.should_shard_solve(num_rows) else None


def _rebuild_topic(
    topic: str,
    members: Sequence[str],
    lags: np.ndarray,
    pids: np.ndarray,
    valid: np.ndarray,
    choice: np.ndarray,
) -> Dict[str, List[TopicPartition]]:
    """Per-member lists for one topic, in processing order, vectorized.

    A stable argsort over the processing-order choice array groups rows per
    consumer while preserving processing order within each consumer.
    """
    P = int(valid.sum())
    lags, pids, choice = lags[:P], pids[:P], choice[:P]
    order = np.lexsort((pids, -lags))
    sorted_choice = choice[order]
    sorted_pids = pids[order]
    grouped = np.argsort(sorted_choice, kind="stable")
    counts = np.bincount(
        sorted_choice[sorted_choice >= 0], minlength=len(members)
    )
    out: Dict[str, List[TopicPartition]] = {}
    pos = int((sorted_choice < 0).sum())  # unassigned rows group first (-1)
    for c, member in enumerate(members):
        rows = grouped[pos : pos + int(counts[c])]
        out[member] = [TopicPartition(topic, int(sorted_pids[i])) for i in rows]
        pos += int(counts[c])
    return out


def assign_group_device(
    group: TopicGroup, kernel: str = "rounds", refine_iters: int = 0
):
    """Run one packed topic group through a batched kernel.

    Returns (choice int32[T, P_pad], counts [T, C], totals) as **device
    arrays** — callers materialize only what they consume, so the rebalance
    path doesn't pay device->host syncs for discarded stats.  ``totals`` is
    per-topic [T, C] for the parity kernels ("rounds"/"scan") but a single
    cross-topic [C] vector for "global" (its totals carry across topics).

    ``refine_iters`` (static, 0 = strict parity; "rounds"/"scan" only)
    chains the per-topic exchange refinement inside the SAME executable —
    the quality mode costs no extra upload or dispatch.
    """
    ensure_x64()
    # The fault point for a half-dead XLA compile: this is where an
    # unwarmed (shape, static-args) combination would block in the
    # compiler, so drills inject their hang/raise here.
    faults.fire("device.compile")
    kernel_fn = _BATCHED_KERNELS[kernel]
    if refine_iters and kernel == "global":
        raise ValueError(
            "refine_iters is per-topic and would undo the 'global' "
            "kernel's cross-topic balance; use kernel='rounds' or 'scan'"
        )
    refine = {"refine_iters": int(refine_iters)} if refine_iters else {}
    if kernel in ("rounds", "global"):
        # Packed single-key sorts when the group's value ranges allow —
        # checked host-side on the numpy inputs (padding rows included:
        # their values only widen the bound).  The totals bound for the
        # packed round body is per-topic row sums for "rounds" but the
        # whole group's sum for "global" (its totals carry across topics).
        max_lag = int(group.lags.max()) if group.lags.size else 0
        max_pid = (
            int(group.partition_ids.max()) if group.partition_ids.size else 0
        )
        shift = pack_shift_for(max_lag, max_pid)
        bound_view = (
            group.lags.reshape(1, -1) if kernel == "global" else group.lags
        )
        rb = totals_rank_bits_for(bound_view, group.num_consumers)
        observe_pack_shift(
            (kernel, group.lags.shape, group.num_consumers),
            (shift, rb),
        )
        return kernel_fn(
            group.lags, group.partition_ids, group.valid,
            num_consumers=group.num_consumers,
            pack_shift=shift,
            totals_rank_bits=rb,
            **refine,
        )
    return kernel_fn(
        group.lags, group.partition_ids, group.valid,
        num_consumers=group.num_consumers,
        **refine,
    )


def assign_device(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    kernel: str = "rounds",
    refine_iters: Optional[int] = None,
) -> AssignmentMap:
    """Device-backed equivalent of the reference's static core (:166-188):
    full parity including empty members and missing-lag topics, with one
    batched kernel launch per subscriber-set group.

    ``refine_iters`` (default off, preserving strict reference parity)
    appends that many rounds of the parallel pairwise-exchange refinement
    (:func:`..ops.batched.refine_batched`) to each group's solve — the
    default solver's quality mode, addressing the slack greedy leaves on
    skewed lags (the reference's own TODO,
    LagBasedPartitionAssignorTest.java:226).  Only the per-topic parity
    kernels accept it: the "global" kernel optimizes CROSS-topic balance,
    which a per-topic refinement would undo."""
    if kernel not in _BATCHED_KERNELS:
        raise ValueError(
            f"unknown kernel {kernel!r}; valid: {sorted(_BATCHED_KERNELS)}"
        )
    # global+refine is rejected by assign_group_device on the first group
    # (the one place the rule lives).
    refine = int(refine_iters) if refine_iters else 0
    assignment: AssignmentMap = {m: [] for m in subscriptions}
    by_topic = consumers_per_topic(subscriptions)
    groups = build_groups(partition_lag_per_topic, by_topic)

    # Dispatch EVERY group before materializing ANY result: JAX dispatch is
    # async, and on a high-latency transport (the tunneled chip: ~50 ms per
    # awaited round-trip, overlapping when in flight together —
    # BASELINE.md) this turns G sequential round-trips into ~one.
    dispatched = [
        (
            group,
            assign_group_device(
                group, kernel=kernel, refine_iters=refine
            )[0],
        )
        for group in groups
    ]

    fragments: Dict[str, Dict[str, List[TopicPartition]]] = {}
    for group, device_choice in dispatched:
        choice = np.asarray(device_choice)
        for ti, topic in enumerate(group.topics):
            fragments[topic] = _rebuild_topic(
                topic,
                group.members,
                group.lags[ti],
                group.partition_ids[ti],
                group.valid[ti],
                choice[ti],
            )

    # Merge fragments in global sorted-topic order so per-member list order
    # matches the oracle exactly (topics sorted, then processing order).
    for topic in sorted(fragments):
        for member, tps in fragments[topic].items():
            assignment[member].extend(tps)
    return assignment


def assign_topic_device(
    topic: str,
    consumers: Sequence[str],
    partition_lags: Sequence[TopicPartitionLag],
    kernel: str = "rounds",
) -> Dict[str, List[TopicPartition]]:
    """Single-topic convenience wrapper (degenerate one-topic group)."""
    result = assign_device(
        {topic: partition_lags},
        {m: [topic] for m in consumers},
        kernel=kernel,
    )
    return result


def assign_per_topic(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    solve_topic,
) -> AssignmentMap:
    """Shared host orchestration for per-topic solvers (Sinkhorn, native):
    dedup + rank members, columnarize rows, call
    ``solve_topic(lags int64[P], pids int32[P], num_consumers) -> choice``
    (any array-like of consumer indices in input row order), and rebuild
    per-member lists with the same reference ordering as the batched path.
    """
    assignment: AssignmentMap = {m: [] for m in subscriptions}
    by_topic = consumers_per_topic(subscriptions)
    # Two-phase for the same reason as assign_device: solve_topic's device
    # dispatch is async, so issue every topic's solve before materializing
    # any result (one overlapped round-trip instead of one per topic).
    dispatched = []
    for topic in sorted(by_topic):
        members = sorted(set(by_topic[topic]))
        rows = partition_lag_per_topic.get(topic, ())
        if not members or not rows:
            continue
        P = len(rows)
        lags = np.fromiter((r.lag for r in rows), np.int64, count=P)
        pids = np.fromiter((r.partition for r in rows), np.int32, count=P)
        dispatched.append(
            (topic, members, lags, pids, P,
             solve_topic(lags, pids, len(members)))
        )
    for topic, members, lags, pids, P, result in dispatched:
        choice = np.asarray(result)[:P]
        frag = _rebuild_topic(
            topic, members, lags, pids, np.ones(P, dtype=bool), choice
        )
        for member, tps in frag.items():
            assignment[member].extend(tps)
    return assignment


__all__ = [
    "QUALITY_MODES",
    "assign_device",
    "assign_group_device",
    "assign_topic_device",
    "autotune_quality_tile",
    "ensure_x64",
    "pad_bucket",
    "quality_mode",
    "quality_scope",
    "quality_status",
    "quality_tile",
    "resolve_quality_mode",
    "set_quality_mode",
    "set_quality_tile",
    "sharded_solve_manager",
]
