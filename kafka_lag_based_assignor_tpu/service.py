"""Sidecar service: the plugin boundary as a wire API.

Deployment model (BASELINE.json north star): the JVM-side
``partition.assignment.strategy`` plugin keeps doing what the reference's
``assign(Cluster, GroupSubscription)`` does — group bookkeeping and the
offset/lag RPCs — and marshals the resulting ``(partition lags,
subscriptions)`` to this co-located sidecar, which runs the TPU solve and
returns the member->partitions map.  Only the combinatorial core crosses
the process boundary, mirroring the L1/L3 split (SURVEY §1).

Protocol: newline-delimited JSON over TCP (trivially implementable from
Java; no schema compiler needed).

Request::

    {"id": 1, "method": "assign",
     "params": {"topics":        {"t0": [[0, 100000], [1, 50000]]},
                "subscriptions": {"C0": ["t0"], "C1": ["t0"]},
                "solver":        "rounds"}}          # optional

Response::

    {"id": 1, "result": {"assignments": {"C0": [["t0", 0]], ...},
                         "stats": {...}}}
    {"id": 1, "error": {"message": "..."}}

Also supported: ``{"method": "ping"}`` -> ``{"result": "pong"}``,
``{"method": "stats"}`` -> counters since start, and
``{"method": "metrics"}`` -> the unified registry (utils/metrics) both
as structured JSON and as the Prometheus text exposition, plus
flight-recorder status (see DEPLOYMENT.md "Observability" and
tools/dump_metrics.py).  One request per line; responses preserve the
request ``id``.  Malformed JSON gets an error response with ``id:
null`` rather than a dropped connection.

Every response envelope additionally carries a server-minted
``request_id`` (``req-<pid>-<n>``): the same id tags package log lines
emitted while the request was being served and any flight-recorder dump
it triggered, so one wire exchange is correlatable across the response,
the logs, and a post-incident dump.  Clients may ignore it.

Streaming mode (the BASELINE config-5 loop as a wire API): a client that
rebalances the same topic periodically can keep warm solver state
server-side instead of paying a from-scratch solve per epoch::

    {"id": 7, "method": "stream_assign",
     "params": {"stream_id": "orders",            # server-side state key
                "topic": "t0",
                "lags": [[0, 100000], [1, 50000]],
                "members": ["C1", "C0"],          # ranks = sorted order
                "options": {"refine_iters": 128,  # exchange budget
                            "guardrail": 1.25,    # or null
                            "refine_threshold": 1.02}}}   # or null

    -> {"id": 7, "result": {"assignments": {"C0": [["t0", 0]], ...},
                            "stream": {"cold_start": true, "refined": ...,
                                       "churn": 0, ...}}}

Epoch-over-epoch the server keeps the previous assignment
(:class:`..ops.streaming.StreamingAssignor`): still-balanced epochs are
no-ops (zero churn), drifted ones pay one bounded refine, and membership
changes remap by member NAME (survivors keep their partitions; see
``remap_members``).  A changed partition-id set or partition count
re-solves cold.  ``{"method": "stream_reset", "params": {"stream_id":
...}}`` drops the state; at most ``MAX_STREAMS`` live streams.  Unlike
``assign`` (processing order, reference :228-235), streaming assignment
lists are in ascending partition-id order — the row-stable order warm
state is keyed on.

Delta epochs (DEPLOYMENT.md "Delta epochs"): steady-state drift touches
few partitions, so instead of re-sending every ``[pid, lag]`` row a
client may send only what changed::

    {"method": "stream_assign",
     "params": {"stream_id": "orders", "members": [...],
                "lag_delta": {"indices": [3, 17],   # partition ids
                              "values": [812, 0],   # their new lags
                              "base_epoch": 41}}}   # last seen lag_epoch

``params.lags`` and ``params.lag_delta`` are mutually exclusive.  Every
stream response reports ``stream.lag_epoch`` — a monotone per-stream
counter of accepted lag vectors — and a delta applies only when its
``base_epoch`` equals the server's current value for the stream
(:mod:`..lag`'s ``LagDeltaTracker`` produces conforming deltas from
consecutive lag reads, so the JVM shim needs no protocol change).  A
stale, duplicate, or gapped ``base_epoch`` — or a server that lost the
base (restart, poisoned-stream rebuild, ``stream_reset``) — forces a
dense re-sync: the response carries ``stream.resync: true`` (serving
the previous assignment unchanged when one is servable, an error
asking for full lags otherwise) and the client must send dense rows
next epoch.  Server-side, the engine diffs every epoch against its
device-resident lag buffer regardless of wire shape, so even
dense-wire deployments get O(changed) device uploads
(``klba_h2d_bytes_total{path=dense|delta}``,
``klba_delta_epochs_total{outcome=applied|fallback|resync}``).

Multi-tenant dispatch coalescing: when MORE than one stream is live,
warm refine epochs route through the megabatch coalescer
(:class:`..ops.coalesce.MegabatchCoalescer`) — concurrent epochs in the
same shape bucket are stacked and served by ONE vmapped fused device
dispatch instead of N serialized round-trips (knobs:
``coalesce_window_ms`` / ``coalesce_max_batch``, config keys
``tpu.assignor.coalesce.window.ms`` / ``tpu.assignor.coalesce.max_batch``;
``max_batch <= 1`` disables).  Consecutive waves from the same stream
set LOCK their roster: the stacked batch buffers stay device-resident
between flushes and rows are index-addressed in place, eliminating the
per-flush re-stack work, and the upload/dispatch/readback flush stages
overlap across waves (knobs ``coalesce_lock_waves`` /
``tpu.assignor.coalesce.roster.lock.waves`` and ``coalesce_pipeline`` /
``tpu.assignor.coalesce.pipeline``; the wire ``stats`` response's
``coalesce`` section tracks locked rosters, hits, and re-stacks).  A
lone stream always takes the inline fast path, so single-tenant
latency is unchanged.  Each live stream
also keeps its OWN small flight-recorder ring (the process-wide
256-record ring stays the aggregate); ``{"method": "stream_flight",
"params": {"stream_id": ..., "clear": false}}`` dumps (and optionally
clears) one stream's ring on demand.  ``metrics_port=`` /
``--metrics-port`` additionally serves the Prometheus text exposition
over plain HTTP (``GET /metrics``, utils/metrics_http) so a stock
Prometheus can scrape without a shim.

Failure model (DEPLOYMENT.md "Failure modes"): every request carries a
deadline budget of ``solve_timeout_s`` TOTAL and descends a degraded-mode
ladder within it — device solve -> host greedy for ``assign``;
warm-resident -> cold device (fresh engine) -> host snake for
``stream_assign``, with the rung taken reported as
``stream.degraded_rung`` (``none`` | ``kept_previous`` | ``cold_device``
| ``host_snake``) and a poisoned stream's next epoch warm-restarting
from the last answered choice (``stream.warm_restart``).  Device calls
run under per-solver circuit breakers (utils/watchdog): a breaker that
is open fails fast — a stream then keeps serving its previous
assignment unchanged (``kept_previous``, warm state intact) — and
``{"method": "stats"}`` exports per-breaker state/trip counters plus
``fallbacks``/``poisoned_snapshots``.

Overload control (utils/overload; DEPLOYMENT.md "Overload and SLOs"):
every stream carries an SLO class (``critical`` | ``standard`` |
``best_effort``; config ``tpu.assignor.slo.class.<stream>``, wire
override ``params.slo_class``) with an optional per-class deadline
budget that caps the request budget and rides into the coalescer as
the epoch's admission deadline — megabatch waves are placed in
(class, remaining deadline) order, and a row whose budget cannot
survive a full flush is re-routed inline or shed.  A service-level
overload detector (EWMA of weighted in-flight depth, windowed
``stream.epoch`` p99, breaker state) walks a shed ladder: shrink the
admission window -> serve ``kept_previous`` for best_effort -> reject
best_effort with a ``retry_after_ms`` hint -> degrade standard; every
shed emits ``klba_shed_total{class,rung}`` and a flight record, and a
rejected request's error envelope carries a structured ``shed``
object.  ``{"method": "recommend"}`` closes the elasticity loop: a
per-stream consumer-count recommendation from each stream's recorded
lag trend plus the current overload state, for the external
autoscaler.

Lifecycle (utils/snapshot; DEPLOYMENT.md "Restarts and recovery"):
with ``snapshot_path`` configured the service periodically (and on
roster churn) writes a versioned, per-section-checksummed, ATOMIC
snapshot of all host-recoverable state — per-stream ``{choice, member
roster, SLO class, lag-trend window}``, breaker states/cooldowns, the
overload rung — and a restarting process rehydrates from it BEFORE
serving: recovered streams are seeded via ``seed_choice`` and their
shapes warmed (megabatch executables included) off the serving path,
so the restart stampede's first warm epochs are bit-identical to what
an uninterrupted process would have produced from the same seeded
choice, with zero compiles.  Per-stream staleness guards apply: a
snapshot older than ``snapshot_max_age_s`` rehydrates nothing, and a
recovered stream whose first post-restart epoch arrives with a drifted
membership or partition set is discarded (cold start) for that stream
only.  Graceful drain — SIGTERM/SIGINT (``install_signal_handlers``)
or the wire ``{"method": "drain"}`` call — stops admissions (new
``assign``/``stream_assign`` requests get a structured reject with a
``retry_after_ms`` hint), waits for in-flight requests and coalescer
waves to flush, writes a final snapshot, then closes the listener;
``{"method": "stats"}`` exports the lifecycle state
(serving/draining/stopped), snapshot age, and last-recovery outcome.

Wire limits: a request line may be at most ``MAX_LINE_BYTES`` (16 MiB —
comfortably above a 100k-partition request, ~2 MB); longer lines are
answered with an error and drained without buffering.  ``params.options``
accepts only ``sinkhorn_iters`` (int, 1..4096) and ``refine_iters`` (int,
0..65536) — these become static jit arguments, so every distinct value
compiles a fresh executable; out-of-range or non-integer values are
rejected as client errors, never silently downgraded to a host fallback,
and accepted values are quantized to a power of two (``sinkhorn_iters``
up — a quality floor; ``refine_iters`` down — a churn ceiling) so a
value-cycling client cannot force unbounded compiles; the effective
values are echoed in the response's ``options`` field (see
``_OPTION_BOUNDS``).
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from .assignor import LagBasedPartitionAssignor
from .models.greedy import assign_greedy, host_fallback_for
from .types import TopicPartitionLag
from .utils import faults, metrics
from .utils import scrub as scrub_lib
from .utils import trace as trace_mod
from .utils.config import VALID_SOLVERS
from .utils.observability import (
    RebalanceStats,
    install_compile_counter,
    summarize_assignment,
)
from .utils.overload import (
    CLASS_WEIGHTS,
    SLO_CLASSES,
    OverloadController,
    ShedReject,
    SloPolicy,
    class_rank,
    recommend_payload,
    record_shed,
)
from .utils.watchdog import SolveRejected, Watchdog

LOGGER = logging.getLogger(__name__)

# Upper bound on one request line.  A north-star-scale assign request
# (100k partitions with 7-digit lags) serializes to ~2 MB; 16 MiB leaves
# ample headroom while preventing a malformed client from streaming an
# unbounded "line" into memory.
MAX_LINE_BYTES = 16 * 1024 * 1024

# params.options whitelist: (min, max) per key.  Both are *static* jit
# arguments downstream — every distinct value costs a fresh XLA compile
# (tens of seconds on this image) — so unknown keys, non-integers, and
# out-of-range values are client errors at the wire boundary, not inputs
# to the solve path.  In-range values are additionally QUANTIZED to a
# power of two (0 stays 0): without quantization a client cycling
# in-range values could force an unbounded number of distinct compiles
# (each cached forever in-process); with it the compile count per key is
# bounded by ~log2(max) executables.  The rounding DIRECTION respects
# what each option promises the client: ``sinkhorn_iters`` is a quality
# floor, so it rounds UP (never less quality than asked); ``refine_iters``
# is the exchange budget whose contract is "churn bounded by 2x this
# value" (ops/refine.py), so it rounds DOWN (never more churn than the
# client permitted).  The effective values are echoed in the response's
# ``options`` field so the substitution is visible on the wire.
_OPTION_BOUNDS = {"sinkhorn_iters": (1, 4096), "refine_iters": (0, 65536)}
_OPTION_ROUNDS_UP = {"sinkhorn_iters": True, "refine_iters": False}

# Live warm-state cap for stream_assign: each stream holds two int32[P]
# vectors (host + device resident) — 64 north-star streams is ~50 MB.
MAX_STREAMS = 64

# Per-stream flight-recorder ring size: one noisy stream's incident no
# longer shares the global 256-record ring with every other tenant.
# Bounded alongside MAX_STREAMS (64 x 64 stats-only records).
STREAM_FLIGHT_CAPACITY = 64

# Wire methods, as metric label values: anything else is labeled
# "unknown" so a misbehaving client cannot mint unbounded label
# cardinality in ``klba_requests_total`` / the span histograms.
_KNOWN_METHODS = frozenset(
    {
        "ping", "stats", "metrics", "assign", "stream_assign",
        "stream_reset", "stream_flight", "recommend", "drain",
        "peer_sync", "federation", "federated_assign", "trace",
    }
)

# Wire encodings for the dense lag payload (DEPLOYMENT.md "Delta
# epochs" — resync-storm compression): ``params.encoding`` selects how
# ``params.lags`` is carried.  "zlib" = base64(zlib(JSON rows)) — the
# post-restart dense resync wave re-sends every stream's full vector
# at once, and those payloads compress ~5-10x.  An UNKNOWN encoding is
# answered with a structured error naming the supported set so the
# client can fall back to plain JSON (the client helper does).
_LAG_ENCODINGS = ("zlib",)

# Lifecycle states (the klba_lifecycle_state gauge exports the index).
_LIFECYCLE_STATES = ("serving", "draining", "stopped")

# Per-stream lag-trend window for the elasticity loop ({"method":
# "recommend"}): (time, total_lag) samples per live stream.  64 epochs
# at a 30 s cadence is a ~30 min trend window — enough slope signal for
# the horizon projection without unbounded growth (lint L014).
STREAM_HISTORY = 64

# Takeover-warming TTL (ROADMAP lifecycle (e)): a recovered stream's
# standing pressure is normally released when its first post-boot
# epoch serves — but a snapshot can carry a stream whose consumer
# group was decommissioned between snapshot and restart, and a weight
# that nothing will ever release must not pin the admission window at
# rung-1 scale for the life of the process.  Any share still parked
# this long after recovery is expired wholesale (checked on the
# admission path, where the held window actually costs something).
# 300 s is ~10 lag-read cadences — far past any real warm-up.
TAKEOVER_WARMING_TTL_S = 300.0


def _counter_total(name: str) -> int:
    """Sum of every series registered under ``name`` — the registry-view
    primitive behind the service ``stats`` counters."""
    return sum(c.value for c in metrics.REGISTRY.series(name))


class _DeadlineBudget:
    """Per-request deadline: the degraded-mode ladder's rungs share ONE
    budget (``solve_timeout_s`` total), so a request answers within the
    configured deadline rather than paying a full timeout per attempt —
    the remaining budget shrinks down the ladder.  ``clock`` is
    injectable (L012 discipline) so budget-consumption accounting is
    testable without real waits."""

    def __init__(
        self,
        total_s: Optional[float],
        clock: Callable[[], float] = time.monotonic,
    ):
        self.total_s = total_s
        self._clock = clock
        self._start = clock()

    def remaining(self) -> Optional[float]:
        """Seconds left (may be <= 0: the watchdog then fails fast
        without charging the breaker); None = no deadline configured."""
        if self.total_s is None:
            return None
        return self.total_s - (self._clock() - self._start)

    def consumed_ms(self) -> float:
        """Milliseconds spent since the budget was minted — the
        deadline-budget-consumption metric, recorded per request."""
        return (self._clock() - self._start) * 1000.0


def _quantize_pow2(value: int, up: bool) -> int:
    if value == 0:
        return 0
    if up:
        return 1 << (value - 1).bit_length()
    return 1 << (value.bit_length() - 1)


def _validate_options(options: Any) -> Dict[str, int]:
    if not isinstance(options, dict):
        raise ValueError("params.options must be a JSON object")
    out: Dict[str, int] = {}
    for key, value in options.items():
        bounds = _OPTION_BOUNDS.get(key)
        if bounds is None:
            raise ValueError(
                f"unknown option {key!r}; valid: {sorted(_OPTION_BOUNDS)}"
            )
        if isinstance(value, bool) or not isinstance(value, int):
            raise ValueError(f"option {key} must be an integer, got {value!r}")
        lo, hi = bounds
        if not lo <= value <= hi:
            raise ValueError(
                f"option {key}={value} out of range [{lo}, {hi}]"
            )
        out[key] = _quantize_pow2(value, _OPTION_ROUNDS_UP[key])
    return out


def _validate_stream_options(options: Any) -> Dict[str, Any]:
    """Stream options: ``refine_iters`` is compile-relevant (static jit
    arg downstream) and gets the same pow2-down quantization as the
    stateless path; ``guardrail`` / ``refine_threshold`` are host-side
    floats (no compile risk) — >= 1.0 or null to disable."""
    if not isinstance(options, dict):
        raise ValueError("params.options must be a JSON object")
    out: Dict[str, Any] = {}
    for key, value in options.items():
        if key == "refine_iters":
            # THE stateless path's validation + pow2-down quantization —
            # delegated so the two surfaces cannot diverge.
            out.update(_validate_options({key: value}))
        elif key in ("guardrail", "refine_threshold"):
            if value is None:
                out[key] = None
                continue
            if isinstance(value, bool) or not isinstance(
                value, (int, float)
            ):
                raise ValueError(f"option {key} must be a number or null")
            if not 1.0 <= float(value) <= 1000.0:
                raise ValueError(
                    f"option {key}={value} out of range [1.0, 1000.0]"
                )
            out[key] = float(value)
        else:
            raise ValueError(
                f"unknown stream option {key!r}; valid: "
                "['guardrail', 'refine_iters', 'refine_threshold']"
            )
    return out


def _host_choice_stats(choice, lags, C: int, prev, cold_start: bool):
    """StreamingStats for an arbitrary host-side choice vector (the
    snake and kept-previous degraded rungs share this evaluation)."""
    import numpy as np

    from .ops.streaming import StreamingStats
    from .utils.observability import count_constrained_bound

    stats = StreamingStats(cold_start=cold_start)
    totals = np.bincount(choice, weights=lags.astype(np.float64),
                         minlength=C)
    mean = totals.mean()
    stats.max_mean_imbalance = float(totals.max() / mean) if mean else 1.0
    stats.imbalance_bound = count_constrained_bound(lags, C)
    counts = np.bincount(choice, minlength=C)
    stats.count_spread = int(counts.max() - counts.min())
    if prev is not None and prev.shape[0] == choice.shape[0]:
        stats.churn = int((choice != prev).sum())
    return stats


def _snake_fallback(lags, C: int, prev):
    """Emergency host-side assignment when the device solve fails or
    times out mid-stream: partitions in descending-lag order deal out
    boustrophedon (round r even -> slot j, odd -> C-1-j) — vectorized,
    count spread <= 1, classic sorted-LPT quality.  NOT reference-parity
    (the streaming surface never was); it keeps the rebalance alive.

    Returns (choice int32[P], StreamingStats-shaped stats)."""
    import numpy as np

    P = lags.shape[0]
    ranks = np.empty(P, np.int64)
    ranks[np.argsort(-lags, kind="stable")] = np.arange(P)
    r, j = np.divmod(ranks, C)
    choice = np.where(r % 2 == 0, j, C - 1 - j).astype(np.int32)
    return choice, _host_choice_stats(choice, lags, C, prev, cold_start=True)


def _parse_lag_delta(delta: Any):
    """Type-validate ``params.lag_delta`` (module docstring "Delta
    epochs"); returns (pids int64[n], values int64[n], base_epoch).
    Only shape/type errors reject here — whether the delta can APPLY
    (base_epoch match, known pids) is decided against the stream's
    stored base under its lock."""
    import numpy as np

    if not isinstance(delta, dict):
        raise ValueError("params.lag_delta must be a JSON object")
    idx = delta.get("indices")
    vals = delta.get("values")
    base = delta.get("base_epoch")
    if not isinstance(idx, list) or not isinstance(vals, list):
        raise ValueError(
            "params.lag_delta.indices/values must be lists"
        )
    if len(idx) != len(vals):
        raise ValueError(
            "params.lag_delta.indices and values differ in length"
        )
    if isinstance(base, bool) or not isinstance(base, int) or base < 0:
        raise ValueError(
            "params.lag_delta.base_epoch must be a non-negative integer"
        )
    d_pids = np.fromiter((int(p) for p in idx), np.int64, count=len(idx))
    d_vals = np.fromiter((int(v) for v in vals), np.int64, count=len(vals))
    if d_vals.size and int(d_vals.min()) < 0:
        raise ValueError("params.lag_delta contains negative lag values")
    if np.unique(d_pids).size != d_pids.size:
        raise ValueError(
            "params.lag_delta.indices contains duplicate partition ids"
        )
    return d_pids, d_vals, base


def _parse_assign_ack(params: Dict[str, Any]) -> Optional[int]:
    """Type-validate ``params.assign_ack`` (module docstring "Delta
    responses"): the assignment epoch whose dense view the client
    holds — opting this request into a delta-encoded answer.  Whether
    the ack is SERVABLE (epoch/roster match) is decided against the
    stream's stored base under its lock."""
    ack = params.get("assign_ack")
    if ack is None:
        return None
    if isinstance(ack, bool) or not isinstance(ack, int) or ack < 0:
        raise ValueError(
            "params.assign_ack must be a non-negative integer"
        )
    return ack


def _parse_accept_encoding(params: Dict[str, Any]) -> Optional[str]:
    """Type-validate ``params.accept_encoding``: opts the client into
    compressed DENSE responses (``assignments_encoded`` as
    base64(zlib(JSON)) — the response half of the resync-storm
    compression whose upload half is ``params.encoding``)."""
    enc = params.get("accept_encoding")
    if enc is None:
        return None
    if enc not in _LAG_ENCODINGS:
        raise ValueError(
            f"unknown accept_encoding {enc!r}; supported: "
            f"{list(_LAG_ENCODINGS)}"
        )
    return enc


def _decode_wire_lags(params: Dict[str, Any]):
    """Resolve ``params.lags`` honoring ``params.encoding`` (module
    docstring "Delta epochs" — resync-storm compression).  Returns the
    plain ``[[pid, lag], ...]`` rows.  ``encoding: "zlib"`` carries the
    rows as base64(zlib(JSON)) — the post-restart dense resync wave
    compresses ~5-10x — counted both ways in
    ``klba_wire_lag_bytes_total{encoding=zlib|plain}`` so the ratio
    reads off one counter pair.  Unknown encodings are a structured
    client error naming the supported set (the client helper falls
    back to plain JSON on it)."""
    rows = params.get("lags")
    enc = params.get("encoding")
    if enc is None or rows in (None, []):
        return rows or []
    if enc not in _LAG_ENCODINGS:
        raise ValueError(
            f"unknown encoding {enc!r}; supported: "
            f"{list(_LAG_ENCODINGS)} — resend params.lags as plain JSON"
        )
    if not isinstance(rows, str):
        raise ValueError(
            "params.lags must be a base64 string when params.encoding "
            "is set"
        )
    import base64
    import zlib

    try:
        blob = base64.b64decode(rows.encode("ascii"), validate=True)
    except (ValueError, UnicodeEncodeError) as exc:
        raise ValueError(f"params.lags is not valid base64: {exc}")
    # Bounded inflate: the wire line cap must hold for the DECODED
    # payload too, or a small compressed bomb would bypass it.
    d = zlib.decompressobj()
    try:
        plain = d.decompress(blob, MAX_LINE_BYTES + 1)
    except zlib.error as exc:
        raise ValueError(f"params.lags failed to decompress: {exc}")
    if len(plain) > MAX_LINE_BYTES or d.unconsumed_tail:
        raise ValueError(
            f"decoded lag payload exceeds {MAX_LINE_BYTES} bytes"
        )
    metrics.REGISTRY.counter(
        "klba_wire_lag_bytes_total", {"encoding": "zlib"}
    ).inc(len(blob))
    metrics.REGISTRY.counter(
        "klba_wire_lag_bytes_total", {"encoding": "plain"}
    ).inc(len(plain))
    decoded = json.loads(plain)
    if not isinstance(decoded, list):
        raise ValueError("decoded params.lags must be a JSON list")
    return decoded


def encode_lags_zlib(rows) -> str:
    """Client half of the ``encoding: "zlib"`` wire shape (the JVM shim
    mirrors this): base64(zlib(JSON rows))."""
    import base64
    import zlib

    return base64.b64encode(
        zlib.compress(json.dumps(rows).encode())
    ).decode("ascii")


def _encode_dense_assignments(
    assignments, resp_enc: Optional[str]
) -> Dict[str, Any]:
    """Wrap a dense assignments dict for the wire, honoring the
    client's ``accept_encoding`` opt-in (the response half of the
    resync-storm compression — a post-restart resync wave is
    compressed in BOTH directions).  Both directions share the byte
    pair ``klba_wire_assign_bytes_total{encoding=zlib|plain}`` so the
    ratio reads off one counter like the upload side's."""
    if resp_enc != "zlib":
        return {"assignments": assignments}
    plain = json.dumps(assignments)
    encoded = encode_lags_zlib(assignments)
    metrics.REGISTRY.counter(
        "klba_wire_assign_bytes_total", {"encoding": "plain"}
    ).inc(len(plain))
    metrics.REGISTRY.counter(
        "klba_wire_assign_bytes_total", {"encoding": "zlib"}
    ).inc(len(encoded))
    return {
        "assignments_encoded": encoded,
        "assignments_encoding": "zlib",
    }


def decode_wire_assignments(result: Dict[str, Any]) -> Dict[str, Any]:
    """Client half of the dense-response encoding: inflate
    ``assignments_encoded`` back into a plain ``assignments`` key
    (bounded, mirroring :func:`_decode_wire_lags`'s inflate cap).
    Results without the encoded key pass through untouched — callers
    can apply this unconditionally."""
    blob = result.get("assignments_encoded")
    if blob is None:
        return result
    enc = result.get("assignments_encoding")
    if enc not in _LAG_ENCODINGS:
        raise ValueError(f"unknown assignments_encoding {enc!r}")
    import base64
    import zlib

    raw = base64.b64decode(blob.encode("ascii"), validate=True)
    d = zlib.decompressobj()
    plain = d.decompress(raw, MAX_LINE_BYTES + 1)
    if len(plain) > MAX_LINE_BYTES or d.unconsumed_tail:
        raise ValueError(
            f"decoded assignments exceed {MAX_LINE_BYTES} bytes"
        )
    out = dict(result)
    out.pop("assignments_encoded")
    out.pop("assignments_encoding")
    out["assignments"] = json.loads(plain)
    return out


def _parse_lag_rows(rows):
    """THE dense-lag row validation both solve surfaces share
    (``stream_assign`` and ``federated_assign``): non-empty, no
    negative lags (the reference's formula clamps at 0 — a negative is
    a client bug), no duplicate pids.  Returns ``(pids_sorted int64[P],
    lags int64[P])`` in ascending-pid order (the row-order contract
    warm state is keyed on)."""
    import numpy as np

    if not rows:
        raise ValueError("params.lags must be a non-empty list")
    pids = np.fromiter(
        (int(p) for p, _ in rows), np.int64, count=len(rows)
    )
    lags_in = np.fromiter(
        (int(lag) for _, lag in rows), np.int64, count=len(rows)
    )
    if lags_in.size and int(lags_in.min()) < 0:
        raise ValueError("params.lags contains negative lag values")
    order = np.argsort(pids, kind="stable")
    pids_sorted = pids[order]
    lags = lags_in[order]
    if pids_sorted.size and (np.diff(pids_sorted) == 0).any():
        raise ValueError("params.lags contains duplicate partition ids")
    return pids_sorted, lags


def _serve_previous(prev, lags, C: int):
    """The kept-previous answer (shed ladder, deadline shed, fail-fast
    fallback alike): the stream's last served choice plus host-computed
    stats for it — zero churn, zero device work, warm state untouched.
    Callers must have checked :func:`_keepable` first."""
    return prev, _host_choice_stats(prev, lags, C, prev, cold_start=False)


def _keepable(prev, P: int, C: int) -> bool:
    """True when the previous choice is directly servable for this epoch:
    complete (no orphaned rows from a membership remap awaiting repair),
    in range, and count-balanced for the current member set."""
    import numpy as np

    if prev is None or prev.shape[0] != P or P == 0:
        return False
    if int(prev.min()) < 0 or int(prev.max()) >= C:
        return False
    counts = np.bincount(prev, minlength=C)
    return int(counts.max() - counts.min()) <= 1


class DrainReject(ShedReject):
    """A request rejected because the sidecar is draining: same
    structured wire shape as an overload shed (class, rung
    ``"draining"``, ``retry_after_ms``) so clients reuse one backoff
    path — but the hint means "retry against another instance", not
    "this one will recover"."""

    def __init__(self, klass: str, retry_after_ms: int):
        RuntimeError.__init__(
            self,
            f"draining: new {klass!r} work is not admitted; retry "
            f"another instance after {retry_after_ms} ms",
        )
        self.klass = klass
        self.rung = "draining"
        self.retry_after_ms = retry_after_ms


class _Stream:
    """Warm per-stream solver state (see the module docstring)."""

    def __init__(self):
        from collections import deque

        self.lock = threading.Lock()
        self.engine = None
        self.members: List[str] = []
        self.pids = None  # np.int64[P], sorted — the row order contract
        self.flight = None  # per-stream FlightRecorder ring
        self.klass = "standard"  # effective SLO class of the last epoch
        # True between snapshot rehydration and the stream's first
        # post-restart epoch: that epoch re-validates the roster — a
        # drifted membership or pid set discards THIS stream's warm
        # state (cold start) instead of remapping a stale roster.
        self.recovered = False
        # (time_s, total_lag) per served epoch — the recommend trend
        # window (bounded: deque maxlen).
        self.history = deque(maxlen=STREAM_HISTORY)
        # Delta-epoch wire state (module docstring "Delta epochs"):
        # the last accepted full lag vector (sorted-pid order) and its
        # monotone epoch counter — the base a ``params.lag_delta``
        # applies to.  Dies with the stream (poison/reset/restart), so
        # a client's next delta answers ``resync`` and re-seeds it
        # dense.
        self.lag_epoch = 0
        self.last_lags = None  # np.int64[P] in st.pids order
        # Assignment-delta wire state (module docstring "Delta
        # responses" — the RESPONSE-side mirror of the lag_delta base):
        # the last SERVED dense answer (members, pids, choice) and its
        # monotone epoch.  A client acking the held epoch gets only the
        # changed rows (``result.assignment_delta``); any mismatch —
        # roster moved, epoch gapped, restart rebuilt the stream —
        # falls back dense, which re-seeds the client's base.  Dies
        # with the stream, exactly like the lag base above.
        self.assign_epoch = 0
        self.last_served = None  # (members list, pids int64[P], choice int32[P])
        # Resident-state quarantine strikes (utils/scrub): forgiven
        # only after FORGIVE_AFTER consecutive clean epochs (a
        # corrupt -> heal -> corrupt flip-flop must still escalate);
        # at ESCALATE_AFTER each further failure also charges the
        # stream breaker — a device that keeps corrupting state is
        # sidelined like one that keeps raising.
        self.scrub_strikes = 0
        self.clean_epochs = 0


def _stream_ring() -> metrics.FlightRecorder:
    """One stream's private flight ring: small, in-memory only (disk
    dumps stay the aggregate recorder's job — dump_dir='' overrides the
    KLBA_FLIGHT_DIR env default)."""
    return metrics.FlightRecorder(
        capacity=STREAM_FLIGHT_CAPACITY, dump_dir=""
    )


def _fresh_engine(
    C: int,
    flight: metrics.FlightRecorder,
    delta_opts: Optional[Dict[str, Any]] = None,
    mesh_backend: Any = None,
):
    """THE service-default engine construction (guardrail ON at 1.25,
    unlike the library default, plus the stream's flight ring, the
    service's delta-epoch knobs, and ITS mesh backend — explicit, so a
    mesh-off service's engines can never adopt a co-resident
    instance's globally activated mesh) — every site that makes an
    engine (first epoch, degraded-ladder cold rung, drift-guard
    rebuild, snapshot rehydration) goes through here, so a recovered
    or rebuilt engine can never drift from a freshly created one and
    silently break the bit-exact recovery contract."""
    from .ops.streaming import StreamingAssignor

    return StreamingAssignor(
        num_consumers=C, imbalance_guardrail=1.25, flight=flight,
        mesh_backend=mesh_backend,
        **(delta_opts or {}),
    )


def _apply_stream_opts(engine, opts: Dict[str, Any]) -> None:
    """Apply validated stream options to a LIVE engine — the one update
    block every epoch (and every ladder rung) uses, so silently ignoring
    a changed budget cannot violate the churn bound the client thinks it
    configured."""
    if "refine_iters" in opts:
        engine.refine_iters = opts["refine_iters"]
    if "guardrail" in opts:
        engine.imbalance_guardrail = opts["guardrail"]
    if "refine_threshold" in opts:
        engine.refine_threshold = opts["refine_threshold"]


def _solve(
    topics, subscriptions, solver, watchdog=None, host_fallback=True,
    options=None, deadline=None,
):
    # Same wire contract as _stream_assign: lags are non-negative by
    # construction (the reference's lag formula clamps at 0), so a
    # negative value is a client-side computation bug — rejected loudly
    # at BOTH entry points, in the same single pass that builds the rows.
    def _row(topic, pid, lag):
        lag = int(lag)
        if lag < 0:
            raise ValueError("params.topics contains negative lag values")
        return TopicPartitionLag(topic, int(pid), lag)

    lag_map = {
        topic: [_row(topic, pid, lag) for pid, lag in rows]
        for topic, rows in topics.items()
    }
    if solver == "global" and (options or {}).get("refine_iters"):
        # Reject at the wire boundary (client error), BEFORE the solver
        # try/except whose fallback would silently return an unrefined
        # assignment while echoing the option back as applied — the same
        # loud rule as config parse and the dispatch layer.
        raise ValueError(
            "options.refine_iters is per-topic and not valid with "
            "solver 'global'"
        )
    subs = {m: list(ts) for m, ts in subscriptions.items()}
    fallback_used = False
    breaker_state = None
    if solver == "host":
        raw = assign_greedy(lag_map, subs)
    else:
        # Same failure model as the in-process plugin adapter
        # (assignor._solve): device solves run under the watchdog — a
        # wedged accelerator transport can HANG rather than raise, and a
        # service request must never block a rebalance past its deadline —
        # with the host greedy as the degraded rung.  The breaker key is
        # the SOLVER (one circuit per failure domain) and the deadline is
        # the request's remaining budget, not a fresh per-attempt window.
        solve = LagBasedPartitionAssignor._solve_accelerated
        try:
            if watchdog is not None:
                raw = watchdog.call(
                    solve, solver, lag_map, subs, options, key=solver,
                    timeout_s=(
                        deadline.remaining() if deadline is not None
                        else watchdog.timeout_s
                    ),
                )
                breaker_state = watchdog.state(solver)
            else:
                raw = solve(solver, lag_map, subs, options)
        except Exception:
            if watchdog is not None:
                breaker_state = watchdog.state(solver)
            if not host_fallback:
                raise
            LOGGER.warning(
                "device solver %r failed; falling back to host greedy",
                solver,
                exc_info=True,
            )
            fallback_used = True
            raw = host_fallback_for(solver)(lag_map, subs)

    stats = RebalanceStats(
        solver=solver,
        num_topics=len(lag_map),
        num_partitions=sum(len(v) for v in lag_map.values()),
        num_members=len(subs),
        # Same operator contract as the in-process plugin: a stats record
        # must say whether the assignment is refined or bit-parity.
        refine_iters=(
            (options or {}).get("refine_iters")
            if solver in ("rounds", "scan", "sinkhorn") and not fallback_used
            else None
        ),
    )
    stats.fallback_used = fallback_used
    stats.breaker_state = breaker_state
    lag_by_tp = {
        (r.topic, r.partition): r.lag for rows in lag_map.values() for r in rows
    }
    stats.total_lag = sum(lag_by_tp.values())
    summarize_assignment(
        stats, raw, {tp: lag_by_tp.get((tp.topic, tp.partition), 0)
                     for tps in raw.values() for tp in tps}
    )
    assignments = {
        m: [[tp.topic, tp.partition] for tp in tps] for m, tps in raw.items()
    }
    return assignments, stats


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        app = self.server.app  # type: ignore[attr-defined]
        while True:
            # Bounded read: readline(n) returns at most n bytes, so an
            # oversized "line" surfaces as a chunk with no trailing newline
            # instead of an unbounded buffer.
            line = self.rfile.readline(MAX_LINE_BYTES + 1)
            if not line:
                break
            try:
                # Fault point: a torn/failed socket read surfaces as a
                # dropped connection — the client's reconnect-once policy
                # (AssignorServiceClient.request) is the recovery path.
                faults.fire("wire.read")
            except faults.FaultError:
                LOGGER.warning("injected wire.read fault; dropping connection")
                break
            if len(line) > MAX_LINE_BYTES and not line.endswith(b"\n"):
                response = app.reject_oversized()
                self.wfile.write(response + b"\n")
                self.wfile.flush()
                if not self._drain_line():
                    break
                continue
            line = line.strip()
            if not line:
                continue
            response = app.handle_line(line)
            self.wfile.write(response + b"\n")
            self.wfile.flush()

    def _drain_line(self) -> bool:
        """Discard the remainder of an oversized line in bounded chunks;
        returns False on EOF."""
        while True:
            chunk = self.rfile.readline(MAX_LINE_BYTES)
            if not chunk:
                return False
            if chunk.endswith(b"\n"):
                return True


#: Per-process instance sequence for lease owner ids: two services in
#: one process (restart drills, the hand-off bench) must be
#: distinguishable to the fencing protocol.
_OWNER_SEQ = iter(range(1, 1 << 30))


class _ResyncPacer:
    """Post-restart resync-storm pacing (ROADMAP delta follow-on (c)):
    a restart wave's first epochs all need a stale-resident DENSE
    rebuild (full-vector upload + table build, dispatched inline —
    a megabatch cannot absorb a per-stream state rebuild), and N
    tenants firing at once used to serialize the device behind one
    dense mega-wave.  This pacer caps how many such rebuilds run
    concurrently; excess epochs wait their turn (bounded by the
    request's own deadline budget — on timeout the epoch proceeds
    UNPACED, fail-open: pacing must never be what fails a request).
    Each wait is counted in ``klba_resync_paced_total``."""

    def __init__(
        self,
        max_inflight: int,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_inflight <= 0:
            raise ValueError(
                f"max_inflight={max_inflight} must be > 0"
            )
        self.max_inflight = int(max_inflight)
        self._cond = threading.Condition()
        self._active = 0
        self._clock = clock
        # High-water mark of concurrent paced rebuilds — the test pin
        # that the cap actually binds (<= max_inflight by design).
        self.high_water = 0
        self._m_paced = metrics.REGISTRY.counter(
            "klba_resync_paced_total"
        )

    def acquire(self, timeout_s: Optional[float]) -> bool:
        """Take a rebuild slot; True when one was taken (the caller
        must :meth:`release`), False when the wait timed out and the
        caller should proceed unpaced."""
        deadline = self._clock() + (
            min(timeout_s, 30.0) if timeout_s is not None else 30.0
        )
        with self._cond:
            if self._active >= self.max_inflight:
                self._m_paced.inc()
                while self._active >= self.max_inflight:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return False  # fail open: dispatch unpaced
                    self._cond.wait(min(remaining, 0.05))
            self._active += 1
            if self._active > self.high_water:
                self.high_water = self._active
            return True

    def release(self) -> None:
        with self._cond:
            self._active -= 1
            self._cond.notify()


class AssignorService:
    """The request processor + TCP front end."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        # Default matches the in-process plugin (utils/config.py): generous
        # enough for a cold first-rebalance XLA compile (~40 s/shape).
        solve_timeout_s: Optional[float] = 120.0,
        host_fallback: bool = True,
        # (max_partitions, num_consumers[, topics]) tuples to pre-compile
        # at startup
        # (VERDICT r3 item 6): without this, a cold sidecar's FIRST assign
        # burns the XLA compile (~40 s/shape through this image's tunnel)
        # inside the rebalance deadline.  ``start()`` runs the warm-up
        # before the accept loop begins serving.  ``warmup_solvers``
        # selects which solver executables to compile (default: every
        # device solver at its default options).  Best-effort coverage:
        # requests at an unwarmed (solver, shape, options) combination —
        # e.g. a sinkhorn request with non-default quantized options, or a
        # topic-batch size not in the warmed buckets — still pay their
        # first compile on demand.
        warmup_shapes: Optional[List[Tuple[int, int]]] = None,
        warmup_solvers: Tuple[str, ...] = (
            "rounds", "stream", "global", "sinkhorn",
        ),
        # Circuit-breaker policy (utils/watchdog): per-solver breakers,
        # consecutive-exception trips, single half-open probe.
        breaker_cooldown_s: float = 300.0,
        breaker_failures: int = 3,
        # Megabatch coalescer (ops/coalesce): admission window for
        # cross-stream warm-epoch batching and the per-shape-bucket
        # batch cap.  max_batch <= 1 disables coalescing entirely;
        # either way a LONE live stream bypasses the coalescer (inline
        # fast path — single-tenant p50 unchanged).
        coalesce_window_ms: float = 0.5,
        coalesce_max_batch: int = 32,
        # Roster-stable fast path: consecutive identical-stream-set
        # waves before a shape group's roster locks (stacked resident
        # batch, index-addressed rows, zero per-flush re-stacks); and
        # the double-buffered upload/dispatch/readback flush pipeline
        # (False = strict-serial fallback).
        coalesce_lock_waves: int = 1,
        coalesce_pipeline: bool = True,
        # Delta epochs (ops/streaming; DEPLOYMENT.md "Delta epochs"):
        # accept sparse lag updates onto the device-resident lag
        # buffer when at most max_fraction of the partitions changed,
        # with a pow2 K ladder of delta_buckets rungs bounding the
        # executable count (the coalescer's stacked delta path uses
        # the ladder top).  delta_enabled=False keeps every upload —
        # wire deltas still apply host-side — dense.
        delta_enabled: bool = True,
        delta_max_fraction: float = 0.125,
        delta_buckets: int = 6,
        # Per-stream adaptive delta cutoff (ops/streaming; ROADMAP
        # delta follow-on (b)): auto-tune each stream's delta/dense
        # cutoff from its observed churn distribution instead of
        # pinning it to delta_max_fraction; the effective value
        # surfaces per stream and in dump_metrics --summary.
        delta_adaptive: bool = True,
        # Multi-device sharding (sharded/; DEPLOYMENT.md "Multi-device
        # sharding"): the mesh spec discovered + validated ONCE at
        # start() — "off" (default), "auto", or a device count — and
        # the partition floor below which the P-sharded solve backend
        # is not selected.  With a mesh active, locked megabatch
        # rosters also spread their stream axis over it.  Degradation
        # (lost device, mesh.collective fault, a sharded dispatch
        # failing) falls back to the single-device backend process-wide
        # and serves in-flight requests down the existing ladder.
        mesh_devices: Any = "off",
        mesh_solve_min_rows: int = 65536,
        # Cross-axis composition (DEPLOYMENT.md "Cross-axis mesh"):
        # the (S, D) ("streams", "p") factorization of the mesh pool —
        # "off" keeps the 1-D rungs, "auto" picks the most square
        # split favouring "p", "SxD" pins it.  On the 2-D rung a
        # locked megabatch of large tenants spreads BOTH axes; faults
        # walk the ladder 2-D -> 1-D streams -> 1-D p -> single.
        mesh_shape: Any = "off",
        # Quality-mode plane (ops/dispatch + ops/linear_ot;
        # DEPLOYMENT.md "Quality modes"): routing between the dense
        # Sinkhorn path and the linear-space O(P + C) mirror-prox path
        # ("auto" picks linear at scale or whenever the mesh elects
        # the P-sharded backend — the two compose), plus the linear
        # mode's streamed tile size (pow2 rows; peak device memory
        # O(tile*C + P + C)).  Installed process-wide at start().
        quality_mode: str = "auto",
        quality_tile: int = 1024,
        # Opt-in plain-HTTP /metrics listener (utils/metrics_http):
        # port to bind on the service host (0 = ephemeral, for tests);
        # None disables.
        metrics_port: Optional[int] = None,
        # SLO classes + overload control (utils/overload): per-stream
        # class map (stream_id -> critical|standard|best_effort; the
        # wire params.slo_class override wins), per-class deadline
        # budgets in SECONDS (each caps that class's request budget
        # below solve_timeout_s and rides into the coalescer as the
        # epoch's admission deadline), and the overload detector's
        # pressure normalizers.  latency budget 0 = auto (half the
        # solve timeout — permissive: an unconfigured sidecar never
        # sheds on cold-compile epochs).
        slo_classes: Optional[Dict[str, str]] = None,
        slo_deadline_s: Optional[Dict[str, float]] = None,
        overload_latency_budget_ms: float = 0.0,
        overload_depth_high: float = 24.0,
        overload_cooldown_s: float = 1.0,
        # Lifecycle snapshots + graceful drain (utils/snapshot;
        # DEPLOYMENT.md "Restarts and recovery").  snapshot_path names
        # the atomic snapshot file (None disables snapshots AND
        # recovery); interval is the periodic cadence (churn events
        # write early, debounced); max_age is the boot-time staleness
        # guard (an older snapshot rehydrates nothing); drain_timeout
        # bounds how long a drain waits for in-flight work before the
        # final snapshot and listener close.
        snapshot_path: Optional[str] = None,
        snapshot_interval_s: float = 30.0,
        snapshot_max_age_s: float = 900.0,
        drain_timeout_s: float = 10.0,
        # Cross-host hand-off (utils/snapshot backends; DEPLOYMENT.md
        # "Cross-host hand-off").  snapshot_backend selects where the
        # snapshot lives: "file" (the per-instance local file, the
        # default), or the object-store-shaped "memory"/"object"
        # backends whose versioned CAS + writer leases let a
        # replacement on ANOTHER host adopt the warm state.  A lease
        # ttl > 0 engages epoch fencing: boot acquires the writer
        # lease (waiting up to lease_wait for a crashed predecessor's
        # lease to expire; 0 = auto, 2x ttl + 1s), every save is
        # save_if(token, prev_version), and a fenced-off predecessor's
        # stale writes are rejected loudly instead of clobbering the
        # adopted state.  Lease acquisition failure FAILS OPEN: the
        # service serves, snapshot writes are denied and counted.
        snapshot_backend: str = "file",
        snapshot_lease_ttl_s: float = 0.0,
        snapshot_lease_wait_s: float = 0.0,
        # Post-restart resync pacing (ROADMAP delta follow-on (c)): at
        # most this many concurrent stale-resident DENSE rebuild
        # dispatches (the full-vector re-sync every recovered stream
        # pays on its first post-restart epoch); excess epochs wait
        # their turn (counted klba_resync_paced_total) so a restart
        # wave trickles through instead of serializing the device
        # behind one dense mega-wave.  <= 0 disables.
        resync_max_inflight: int = 8,
        # Pre-stack recovered rosters at boot (ROADMAP lifecycle (b)):
        # rebuild each recovered stream's device-resident warm state
        # from its seeded choice (zero-lag build, off the serving
        # path) so the storm's first epochs skip the inline dense
        # table-build and coalesce like steady-state traffic.  The
        # restart_storm bench measures this both ways.
        recovery_prestack: bool = False,
        # Resident-state scrubber (utils/scrub; DEPLOYMENT.md "State
        # integrity"): background cadence for auditing idle streams'
        # device-resident buffers (choice/row_tab/counts/lags) against
        # their host mirrors.  Off the serving path: each pass is
        # deadline-budgeted, only idle streams are audited (the stream
        # lock is taken non-blocking), and the whole pass is skipped
        # while the overload ladder is at rung >= 2.  A failed audit
        # quarantines the stream (resident dropped; the next epoch
        # rebuilds bit-exact from host truth) and repeated failures
        # escalate to the stream breaker.  <= 0 disables.
        scrub_interval_ms: float = 30_000.0,
        # Federated multi-cluster assignment (federated/;
        # DEPLOYMENT.md "Federated assignment"): this sidecar's stable
        # peer id plus the peer sidecars ("id=host:port,..." or a
        # parsed PeerSpec list).  With both set, the sidecar answers
        # ``peer_sync`` over its local lag shard and serves
        # ``federated_assign`` by running synchronized dual-exchange
        # rounds against every peer inside the request's deadline
        # budget — only consumer-axis duals/marginals cross the wire,
        # never raw lags.  Per-peer circuit breakers ride the service
        # watchdog (keys ``peer:<id>``); any incomplete round degrades
        # last-good-global -> local-only (today's single-cluster
        # behavior), bounded by federation_max_staleness_s.
        federation_self_id: Optional[str] = None,
        federation_peers: Any = None,
        federation_rounds: int = 16,
        federation_sync_timeout_s: float = 2.0,
        federation_max_staleness_s: float = 300.0,
        # Async gossip duals (ISSUE 19): > 0 starts the background
        # dual-convergence daemon at that jittered cadence (seconds),
        # so federated_assign serves rung global from the warm cache
        # in one local round; 0 keeps every exchange synchronous.
        federation_gossip_interval_s: float = 0.0,
        # Weighted shards (ROADMAP federated (c)): this cluster's
        # per-consumer capacity weight vector (list of positive
        # floats), exchanged in the hello handshake and summed into
        # the capacity-weighted count marginal; None contributes
        # uniform weights.
        federation_capacity: Optional[List[float]] = None,
        # False skips the recovered-shape warm-up pass in start()
        # (tests/drills that assert recovery semantics without paying
        # compiles); production keeps it on — it is what makes the
        # restart stampede compile-free.
        recovery_warmup: bool = True,
        # Uptime/budget clock (L012 discipline: injectable, monotonic).
        clock: Callable[[], float] = time.monotonic,
    ):
        # Knob validation BEFORE any resource (socket) is acquired: a
        # bad delta knob must fail the boot loudly, not error every
        # stream_assign once the first engine is built.
        if not 0.0 < float(delta_max_fraction) <= 1.0:
            raise ValueError(
                f"delta_max_fraction={delta_max_fraction} must be in "
                "(0, 1]"
            )
        if int(delta_buckets) < 0:
            raise ValueError(
                f"delta_buckets={delta_buckets} must be >= 0"
            )
        from .utils.snapshot import BACKEND_KINDS

        if snapshot_backend not in BACKEND_KINDS:
            raise ValueError(
                f"snapshot_backend={snapshot_backend!r} invalid; "
                f"choose one of {list(BACKEND_KINDS)}"
            )
        if float(snapshot_lease_ttl_s) < 0:
            raise ValueError(
                f"snapshot_lease_ttl_s={snapshot_lease_ttl_s} must be "
                ">= 0"
            )
        self._tcp = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True
        )
        self._tcp.daemon_threads = True
        self._tcp.app = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._watchdog = Watchdog(
            solve_timeout_s,
            cooldown_s=breaker_cooldown_s,
            failure_threshold=breaker_failures,
        )
        self._host_fallback = host_fallback
        # Normalize (P, C) -> (P, C, topics=1).
        self._warmup_shapes = [
            (s[0], s[1], s[2] if len(s) > 2 else 1)
            for s in (warmup_shapes or [])
        ]
        self._warmup_solvers = tuple(warmup_solvers)
        self._streams: Dict[str, _Stream] = {}
        self._streams_lock = threading.Lock()
        # Last-answered choice per POISONED stream (host-side snapshot):
        # the next epoch warm-restarts from what the clients are actually
        # running instead of paying a full cold solve.  Bounded alongside
        # the stream cap; consumed (popped) on use or stream_reset.
        self._snapshots: Dict[str, Tuple] = {}
        # Delta-epoch knobs (validated above, before the socket bind),
        # threaded into every engine construction (_fresh_engine) and —
        # as the single stacked K, the engines' ladder top — the
        # coalescer's locked delta path.
        self._delta_opts = {
            "delta_enabled": bool(delta_enabled),
            "delta_max_fraction": float(delta_max_fraction),
            "delta_buckets": int(delta_buckets),
            "delta_adaptive": bool(delta_adaptive),
        }
        # Mesh manager (sharded/mesh): constructed here — cheap and
        # inert — but discovered/validated in start() (never per
        # request) and installed as the process-wide backend-selection
        # input there.  None when the knob is "off".
        from .sharded.mesh import MeshManager, _parse_spec

        self._mesh = (
            MeshManager(
                devices=mesh_devices,
                solve_min_rows=int(mesh_solve_min_rows),
                shape=mesh_shape,
            )
            if _parse_spec(mesh_devices) != "off"
            else None
        )
        # Quality-plane knobs: validated HERE (fail at construction,
        # not at the first quality solve) but installed process-wide
        # in start() — a constructed-but-never-started instance must
        # not clobber a live sibling's routing.
        from .ops.dispatch import normalize_quality_mode
        from .utils.config import validate_quality_tile

        self._quality_mode = normalize_quality_mode(quality_mode)
        self._quality_tile = validate_quality_tile(quality_tile)
        # What the warm-up drives: 0 rungs when delta mode is off.
        self._warm_delta_buckets = (
            int(delta_buckets) if delta_enabled else 0
        )
        from .ops.streaming import delta_k_ladder

        ladder = delta_k_ladder(delta_buckets) if delta_enabled else []
        delta_k = ladder[-1] if ladder else 0
        if coalesce_max_batch > 1:
            from .ops.coalesce import MegabatchCoalescer

            self._coalescer = MegabatchCoalescer(
                window_s=max(float(coalesce_window_ms), 0.0) / 1000.0,
                max_batch=int(coalesce_max_batch),
                lock_waves=int(coalesce_lock_waves),
                pipeline=bool(coalesce_pipeline),
                delta_k=delta_k,
                mesh_manager=self._mesh,
            )
        else:
            self._coalescer = None
        self._metrics_port = metrics_port
        self._metrics_http = None
        # SLO policy + the overload controller (utils/overload): the
        # shed ladder walks on the stream breaker's state plus
        # registry-fed depth/latency pressure.
        self._slo = SloPolicy(
            classes=slo_classes, deadline_s=slo_deadline_s
        )
        self._overload = OverloadController(
            latency_budget_ms=(
                overload_latency_budget_ms if overload_latency_budget_ms > 0
                else (solve_timeout_s or 120.0) * 500.0
            ),
            depth_high=overload_depth_high,
            cooldown_s=overload_cooldown_s,
            breaker_open=lambda: self._watchdog.state("stream") == "open",
        )
        # Weighted in-flight stream-request depth (the controller's
        # queue signal); guarded by its own leaf lock.
        self._inflight_lock = threading.Lock()
        self._inflight_weight = 0.0
        # The request/error/fallback counters live in the registry
        # (klba_requests_total / klba_request_errors_total /
        # klba_fallbacks_total — the same series a scraper reads); the
        # wire ``stats`` shape is a DELTA VIEW over them, baselined at
        # construction so per-instance semantics survive the registry
        # being process-wide (tests spin up many services per process).
        self._stats_base = {
            "requests_served": _counter_total("klba_requests_total"),
            "errors": _counter_total("klba_request_errors_total"),
            "fallbacks": _counter_total("klba_fallbacks_total"),
        }
        self._clock = clock
        self._started = clock()
        # Lifecycle (module docstring "Lifecycle"): the serving/
        # draining/stopped state machine, the snapshot store + periodic
        # writer, and the drain bookkeeping.  The state gate is read on
        # every admission, so it is a plain attribute (GIL-atomic read)
        # mutated only under the lifecycle lock.
        self._lifecycle = "serving"
        self._lifecycle_lock = threading.Lock()
        self._listener_closed = False
        self._drain_timeout_s = float(drain_timeout_s)
        self._drain_thread: Optional[threading.Thread] = None
        self._stopped_event = threading.Event()
        self._active_cond = threading.Condition()
        self._active_requests = 0
        self._last_recovery: Optional[Dict[str, Any]] = None
        # (P, C) shapes discovered during recovery: warmed via the
        # stream/megabatch warm-up in start(), OFF the serving path, so
        # the restart stampede's first warm epochs compile nothing.
        # noqa: L014 — appended only during boot recovery, bounded by
        # MAX_STREAMS rehydrated streams.
        self._recovery_shapes: List[Tuple[int, int]] = []  # noqa: L014
        self._snapshot_max_age_s = float(snapshot_max_age_s)
        self._recovery_warmup = bool(recovery_warmup)
        self._m_lifecycle = metrics.REGISTRY.gauge("klba_lifecycle_state")
        self._m_lifecycle.set(0)
        # Cross-host hand-off state: the boot-time lease handshake's
        # outcome (wire stats "lifecycle.handoff"; None until start()).
        self._last_handoff: Optional[Dict[str, Any]] = None
        self._lease_wait_s = (
            float(snapshot_lease_wait_s)
            if snapshot_lease_wait_s > 0
            else float(snapshot_lease_ttl_s) * 2.0 + 1.0
        )
        self._recovery_prestack = bool(recovery_prestack)
        # Takeover-warming ledger (ROADMAP lifecycle (e)): per-stream
        # CLASS_WEIGHTS parked as the overload controller's STANDING
        # pressure while a recovered/adopted stream has not yet served
        # its first post-boot epoch.  Guarded by _streams_lock;
        # released stream by stream (first epoch / reset / discard /
        # poison) so the admission window returns to full scale
        # exactly when the takeover warm-up has drained — or wholesale
        # at the TTL (a dead stream in the snapshot must not pin the
        # window forever; see TAKEOVER_WARMING_TTL_S).
        self._takeover_warming: Dict[str, float] = {}
        self._takeover_deadline: Optional[float] = None
        if scrub_interval_ms and float(scrub_interval_ms) > 0:
            self._scrubber = scrub_lib.StateScrubber(
                targets=self._scrub_targets,
                interval_s=float(scrub_interval_ms) / 1000.0,
                suppress=lambda: self._overload.rung() >= 2,
            )
        else:
            self._scrubber = None
        self._resync_pacer = (
            _ResyncPacer(int(resync_max_inflight), clock=clock)
            if int(resync_max_inflight) > 0 else None
        )
        if snapshot_path:
            from .utils.snapshot import (
                SnapshotStore,
                SnapshotWriter,
                build_backend,
            )

            self._snapshot_store = SnapshotStore(
                backend=build_backend(snapshot_backend, snapshot_path)
            )
            if snapshot_lease_ttl_s > 0:
                # The owner id must be unique per INSTANCE, not per
                # process: the hand-off drills run two instances in
                # one process and fencing must tell them apart.
                import os

                owner = (
                    f"{socket.gethostname()}:{os.getpid()}:"
                    f"{next(_OWNER_SEQ)}"
                )
                self._snapshot_store.attach_lease(
                    owner, float(snapshot_lease_ttl_s)
                )
            self._snapshot_writer = SnapshotWriter(
                self._snapshot_store,
                self._snapshot_sections,
                interval_s=float(snapshot_interval_s),
            )
        else:
            self._snapshot_store = None
            self._snapshot_writer = None
        # Federated peer coordination (federated/peers): built only
        # when configured — a single-cluster sidecar pays nothing.
        # The per-peer breakers live on the SERVICE watchdog (keys
        # ``peer:<id>``), so ``stats.breakers`` shows sidelined peers
        # next to sidelined solvers; the fencing token is read lazily
        # from the snapshot store's writer lease, so a fenced-off
        # predecessor's sync requests are rejected by its peers with
        # the same token that fences its snapshot writes.
        if federation_self_id:
            from .federated import FederationCoordinator, parse_peer_specs

            specs = federation_peers or []
            if isinstance(specs, str):
                specs = parse_peer_specs(specs)
            self._federation = FederationCoordinator(
                self_id=str(federation_self_id),
                peers=list(specs),
                watchdog=self._watchdog,
                max_rounds=int(federation_rounds),
                sync_timeout_s=float(federation_sync_timeout_s),
                max_staleness_s=float(federation_max_staleness_s),
                fence_token=self._federation_fence_token,
                clock=clock,
                capacity=federation_capacity,
                gossip_interval_s=float(federation_gossip_interval_s),
            )
        else:
            if federation_peers:
                raise ValueError(
                    "federation_peers requires federation_self_id"
                )
            self._federation = None

    @property
    def requests_served(self) -> int:
        """Registry view: wire requests answered since THIS service was
        constructed (ROADMAP "registry-backed stats").

        Known tradeoff of the fold: the registry is process-wide, so
        with TWO services alive CONCURRENTLY in one process each
        instance's delta also counts the other's traffic (per-instance
        label sets would mint unbounded series cardinality across test
        processes, which the registry deliberately forbids).  The
        deployment topologies run one sidecar per process; sequential
        instances (tests) are exact via the construction baseline.
        Reads are lock-free counter sums — the requests/errors/
        fallbacks triple in one ``stats`` response may be mutually torn
        by in-flight requests, like any monitoring-counter scrape."""
        return (
            _counter_total("klba_requests_total")
            - self._stats_base["requests_served"]
        )

    @property
    def errors(self) -> int:
        return (
            _counter_total("klba_request_errors_total")
            - self._stats_base["errors"]
        )

    @property
    def fallbacks(self) -> int:
        """Responses answered by a host-side fallback rung."""
        return (
            _counter_total("klba_fallbacks_total")
            - self._stats_base["fallbacks"]
        )

    @classmethod
    def from_config(
        cls,
        configs,
        host: str = "127.0.0.1",
        port: int = 0,
        **overrides,
    ) -> "AssignorService":
        """Build a sidecar from a Kafka-style consumer config map — THE
        consumer of the service-relevant ``tpu.assignor.*`` keys
        (utils/config.parse_config): ``solve.timeout.ms``,
        ``host.fallback``, ``breaker.cooldown.ms`` / ``breaker.failures``,
        ``coalesce.window.ms`` / ``coalesce.max_batch``,
        ``slo.class.<stream>`` / ``slo.deadline.ms.<class>`` /
        ``overload.*``, ``snapshot.path`` / ``snapshot.interval.ms`` /
        ``snapshot.max.age.ms`` / ``drain.timeout.ms``, and
        ``metrics.port``.  An embedder that already holds the consumer
        config (which always carries the required ``group.id``) gets a
        service whose knobs agree with the plugin's, one parse for both
        surfaces.  Explicit ``overrides`` kwargs win over config values
        (e.g. ``warmup_shapes``, or a test pinning ``metrics_port=0``).
        """
        from .utils.config import parse_config

        cfg = parse_config(configs)
        kwargs = {
            "solve_timeout_s": cfg.solve_timeout_s,
            "host_fallback": cfg.host_fallback,
            "breaker_cooldown_s": cfg.breaker_cooldown_s,
            "breaker_failures": cfg.breaker_failures,
            "coalesce_window_ms": cfg.coalesce_window_s * 1000.0,
            "coalesce_max_batch": cfg.coalesce_max_batch,
            "coalesce_lock_waves": cfg.coalesce_lock_waves,
            "coalesce_pipeline": cfg.coalesce_pipeline,
            "delta_enabled": cfg.delta_enabled,
            "delta_max_fraction": cfg.delta_max_fraction,
            "delta_buckets": cfg.delta_buckets,
            "delta_adaptive": cfg.delta_adaptive,
            "mesh_devices": cfg.mesh_devices,
            "mesh_solve_min_rows": cfg.mesh_solve_min_rows,
            "mesh_shape": cfg.mesh_shape,
            "quality_mode": cfg.quality_mode,
            "quality_tile": cfg.quality_tile,
            "metrics_port": cfg.metrics_port,
            "snapshot_path": cfg.snapshot_path,
            "snapshot_interval_s": cfg.snapshot_interval_s,
            "snapshot_max_age_s": cfg.snapshot_max_age_s,
            "drain_timeout_s": cfg.drain_timeout_s,
            "snapshot_backend": cfg.snapshot_backend,
            "snapshot_lease_ttl_s": cfg.snapshot_lease_ttl_s,
            "snapshot_lease_wait_s": cfg.snapshot_lease_wait_s,
            "resync_max_inflight": cfg.resync_max_inflight,
            "recovery_prestack": cfg.recovery_prestack,
            "scrub_interval_ms": cfg.scrub_interval_s * 1000.0,
            "federation_self_id": cfg.federation_self_id,
            "federation_peers": cfg.federation_peers or None,
            "federation_rounds": cfg.federation_rounds,
            "federation_sync_timeout_s": cfg.federation_sync_timeout_s,
            "federation_max_staleness_s": cfg.federation_max_staleness_s,
            "federation_gossip_interval_s": (
                cfg.federation_gossip_interval_s
            ),
            "federation_capacity": cfg.federation_capacity,
            "warmup_shapes": cfg.warmup_shapes or None,
            "slo_classes": cfg.slo_classes,
            "slo_deadline_s": cfg.slo_deadline_s,
            "overload_latency_budget_ms": cfg.overload_latency_budget_ms,
            "overload_depth_high": cfg.overload_depth_high,
        }
        kwargs.update(overrides)
        return cls(host, port, **kwargs)

    @property
    def address(self) -> Tuple[str, int]:
        return self._tcp.server_address  # type: ignore[return-value]

    # -- request processing ------------------------------------------------

    def reject_oversized(self) -> bytes:
        metrics.REGISTRY.counter(
            "klba_request_errors_total", {"method": "oversized"}
        ).inc()
        LOGGER.warning("rejected oversized request line (> %d bytes)",
                       MAX_LINE_BYTES)
        return json.dumps(
            {
                "id": None,
                "request_id": metrics.mint_request_id(),
                "error": {
                    "message": f"request line exceeds {MAX_LINE_BYTES} bytes"
                },
            }
        ).encode()

    def handle_line(self, line: bytes) -> bytes:
        """One wire request: minted request id (echoed in the response
        envelope and on request-thread log lines), a ``wire.<method>``
        span, and deadline-budget-consumption accounting."""
        with self._active_cond:
            # Drain bookkeeping: the drain worker waits for this count
            # to reach zero before flushing and closing the listener.
            self._active_requests += 1
        try:
            return self._handle_line_counted(line)
        finally:
            with self._active_cond:
                self._active_requests -= 1
                self._active_cond.notify_all()

    def _handle_line_counted(self, line: bytes) -> bytes:
        # Parse BEFORE opening the scope: the trace context rides the
        # request line (top-level ``traceparent``, or inside ``params``
        # for the audited federated envelope), and the scope is the
        # trace root — it must adopt the caller's context at birth.  A
        # parse failure still answers from inside a (self-rooted)
        # scope, so the error envelope shape is unchanged.
        req: Dict[str, Any] = {}
        parse_error: Optional[Exception] = None
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                req, parse_error = {}, TypeError(
                    f"request must be a JSON object, got "
                    f"{type(req).__name__}"
                )
        except Exception as exc:  # noqa: L011 — re-raised in-scope below
            parse_error = exc
        traceparent = req.get("traceparent")
        if traceparent is None:
            params = req.get("params")
            if isinstance(params, dict):
                traceparent = params.get("traceparent")
        with metrics.request_scope(traceparent=traceparent) as rid:
            trace_id = metrics.current_trace_id()
            req_id = req.get("id")
            label = "unknown"
            try:
                if parse_error is not None:
                    raise parse_error
                method = req.get("method")
                if method in _KNOWN_METHODS:
                    label = method
                with metrics.span(f"wire.{label}"):
                    result, budget = self._dispatch(method, req)
                metrics.REGISTRY.counter(
                    "klba_requests_total", {"method": label}
                ).inc()
                if budget is not None and budget.total_s is not None:
                    metrics.REGISTRY.histogram(
                        "klba_deadline_budget_consumed_ms",
                        {"method": label},
                    ).observe(budget.consumed_ms())
                return json.dumps(
                    {
                        "id": req_id, "request_id": rid,
                        "trace_id": trace_id, "result": result,
                    }
                ).encode()
            except ShedReject as exc:
                # An overload shed is a DECISION, not a failure: counted
                # as a served request (the shed itself is accounted in
                # klba_shed_total), answered as a structured error the
                # client can back off on.
                metrics.REGISTRY.counter(
                    "klba_requests_total", {"method": label}
                ).inc()
                LOGGER.warning("request shed: %s", exc)
                return json.dumps(
                    {
                        "id": req_id,
                        "request_id": rid,
                        "trace_id": trace_id,
                        "error": {
                            "message": str(exc),
                            "shed": {
                                "class": exc.klass,
                                "rung": exc.rung,
                                "retry_after_ms": exc.retry_after_ms,
                            },
                        },
                    }
                ).encode()
            except Exception as exc:  # noqa: BLE001 — wire boundary
                trace_mod.mark("error")
                metrics.REGISTRY.counter(
                    "klba_request_errors_total", {"method": label}
                ).inc()
                LOGGER.warning("service request failed", exc_info=True)
                return json.dumps(
                    {
                        "id": req_id,
                        "request_id": rid,
                        "trace_id": trace_id,
                        "error": {"message": str(exc)},
                    }
                ).encode()

    def _dispatch(
        self, method: Any, req: Dict[str, Any]
    ) -> Tuple[Any, Optional[_DeadlineBudget]]:
        """Route one parsed request; returns (result, deadline budget)."""
        if method == "ping":
            return "pong", None
        if method == "stats":
            # The wire shape is a VIEW over the registry series (see the
            # properties above) — no shadow counters to keep in sync.
            result: Dict[str, Any] = {
                "requests_served": self.requests_served,
                "errors": self.errors,
                "fallbacks": self.fallbacks,
                "uptime_s": self._clock() - self._started,
            }
            with self._streams_lock:
                result["live_streams"] = len(self._streams)
                result["poisoned_snapshots"] = len(self._snapshots)
            # Per-solver circuit-breaker states + trip counters — the
            # operator's view of which failure domains are sidelined.
            result["breakers"] = self._watchdog.stats()
            # The shed ladder's position + pressure signals
            # (utils/overload; see DEPLOYMENT.md "Overload and SLOs").
            result["overload"] = self._overload.snapshot()
            if self._coalescer is not None:
                # Roster tracking: how many shape groups currently
                # serve on the locked fast path, plus the hit /
                # re-stack / invalidation / dead-row counters (see
                # DEPLOYMENT.md "Multi-tenant throughput").
                result["coalesce"] = self._coalescer.stats()
            # Lifecycle: serving/draining/stopped, snapshot age, and
            # the last recovery's outcome (DEPLOYMENT.md "Restarts
            # and recovery"; tools/dump_metrics.py --summary).
            result["lifecycle"] = self.lifecycle_stats()
            # Resident-state scrubber coverage + quarantine counts
            # (DEPLOYMENT.md "State integrity"); None when disabled.
            result["scrub"] = self.scrub_stats()
            # Federated peer coordination (DEPLOYMENT.md "Federated
            # assignment"); None when not configured.
            result["federation"] = (
                self._federation.status()
                if self._federation is not None else None
            )
            # Multi-device mesh (DEPLOYMENT.md "Multi-device
            # sharding"); None when tpu.assignor.mesh.devices=off.
            result["mesh"] = (
                self._mesh.status() if self._mesh is not None else None
            )
            # Quality-mode plane (DEPLOYMENT.md "Quality modes"):
            # mode/tile knobs + the last linear solve's tile count and
            # peak-memory estimate (dump_metrics --summary rows).
            from .ops.dispatch import quality_status

            result["quality"] = quality_status()
            # Fault injection (utils/faults; scenarios/ drills): the
            # active injector's seed + per-point {calls, fired}
            # counters so a wire-level driver can verify its planned
            # faults actually landed; None when no drill is active.
            inj = faults.active()
            result["faults"] = (
                None if inj is None
                else {
                    "seed": inj.seed,
                    "epoch": inj.epoch,
                    "points": inj.snapshot(),
                }
            )
            return result, None
        if method == "metrics":
            # The registry, both ways: structured JSON for programmatic
            # consumers, Prometheus text exposition for scrapers (see
            # tools/dump_metrics.py and DEPLOYMENT.md "Observability").
            # ``params.view`` ("json" | "prometheus" | "flight") trims
            # the response to one section — a 15 s scrape loop should
            # not ship the snapshot twice plus the last dump per poll;
            # either way the registry is walked ONCE.
            view = (req.get("params") or {}).get("view")
            if view not in (None, "json", "prometheus", "flight"):
                raise ValueError(
                    f"unknown metrics view {view!r}; valid: "
                    "['flight', 'json', 'prometheus']"
                )
            result = {}
            if view in (None, "json", "prometheus"):
                snap = metrics.REGISTRY.snapshot()
                if view in (None, "json"):
                    result["json"] = snap
                if view in (None, "prometheus"):
                    result["prometheus"] = metrics.REGISTRY.prometheus(
                        snap
                    )
            if view in (None, "flight"):
                last = metrics.FLIGHT.last_dump()
                result["flight"] = {
                    "records": len(metrics.FLIGHT.records()),
                    "dumps": metrics.FLIGHT.dump_count(),
                    "last_dump_reason": last["reason"] if last else None,
                    # The payload itself: with KLBA_FLIGHT_DIR unset
                    # (the default) the wire is the ONLY way an
                    # operator can reach a dump post-incident.
                    "last_dump": last,
                }
            return result, None
        if method == "trace":
            # The tail-sampler's wire view (utils/trace): retention
            # stats plus kept traces — ``params.trace_id`` narrows to
            # one trace's segments (a cross-process trace has one
            # segment per participating scope), ``params.limit`` caps
            # the kept-trace payload (default 8, newest last).
            params = req.get("params") or {}
            want = params.get("trace_id")
            if want is not None and not isinstance(want, str):
                raise ValueError(
                    f"trace_id must be a string, got "
                    f"{type(want).__name__}"
                )
            limit = params.get("limit", 8)
            limit = None if limit is None else int(limit)
            coll = trace_mod.COLLECTOR
            return {
                "stats": coll.stats(),
                "traces": coll.traces(trace_id=want, limit=limit),
            }, None
        if method == "drain":
            # Graceful drain over the wire (same path as SIGTERM): the
            # response answers IMMEDIATELY with the lifecycle state —
            # the drain itself (quiesce, final snapshot, listener
            # close) runs on its own thread so this connection still
            # gets its reply before the listener goes away.
            initiated = self.begin_drain()
            return {
                "state": self._lifecycle,
                "initiated": initiated,
            }, None
        if method == "assign":
            self._reject_if_draining("standard")
            params = req.get("params") or {}
            solver = params.get("solver", "rounds")
            if solver not in VALID_SOLVERS:
                raise ValueError(
                    f"unknown solver {solver!r}; valid: {list(VALID_SOLVERS)}"
                )
            options = _validate_options(params.get("options") or {})
            budget = _DeadlineBudget(
                self._watchdog.timeout_s, clock=self._clock
            )
            assignments, stats = _solve(
                params.get("topics") or {},
                params.get("subscriptions") or {},
                solver,
                watchdog=self._watchdog,
                host_fallback=self._host_fallback,
                options=options,
                deadline=budget,
            )
            rung = "host_greedy" if stats.fallback_used else "none"
            metrics.REGISTRY.counter(
                "klba_ladder_rung_total", {"method": "assign", "rung": rung}
            ).inc()
            metrics.FLIGHT.record(
                "wire_assign",
                {
                    "solver": solver,
                    "rung": rung,
                    "num_partitions": stats.num_partitions,
                    "num_members": stats.num_members,
                    "total_lag": stats.total_lag,
                    "quality_ratio": stats.quality_ratio,
                    "fallback_used": stats.fallback_used,
                    "breaker_state": stats.breaker_state,
                },
            )
            if stats.fallback_used:
                metrics.REGISTRY.counter(
                    "klba_fallbacks_total", {"method": "assign"}
                ).inc()
                trace_mod.mark("ladder")
                metrics.FLIGHT.auto_dump(
                    "ladder",
                    {"method": "assign", "rung": rung, "solver": solver},
                )
            return {
                "assignments": assignments,
                "stats": json.loads(stats.to_json()),
                # Effective (quantized) option values actually used —
                # a client can see any pow2 substitution on the wire.
                "options": options,
            }, budget
        if method == "stream_assign":
            params = req.get("params") or {}
            # SLO class: wire override > config map > "standard"; the
            # class's deadline budget (if configured) caps this
            # request's budget below the global solve timeout.
            klass = self._slo.resolve(
                params.get("stream_id"), params.get("slo_class")
            )
            self._reject_if_draining(klass)
            budget = _DeadlineBudget(
                self._slo.budget_s(klass, self._watchdog.timeout_s),
                clock=self._clock,
            )
            result = self._stream_assign(params, budget, klass)
            rung = result["stream"]["degraded_rung"]
            metrics.REGISTRY.counter(
                "klba_ladder_rung_total",
                {"method": "stream_assign", "rung": rung},
            ).inc()
            if result["stream"]["fallback_used"]:
                metrics.REGISTRY.counter(
                    "klba_fallbacks_total", {"method": "stream_assign"}
                ).inc()
            s = result["stream"]
            metrics.FLIGHT.record(
                "wire_stream",
                {
                    "rung": rung,
                    "cold_start": s["cold_start"],
                    "refined": s["refined"],
                    "guardrail_tripped": s["guardrail_tripped"],
                    "churn": s["churn"],
                    "quality_ratio": s["quality_ratio"],
                    "warm_restart": s["warm_restart"],
                    "fallback_used": s["fallback_used"],
                    "slo_class": s["slo_class"],
                    "shed": s["shed"],
                },
            )
            if rung != "none":
                # Descended past the first ladder rung: a flight-recorder
                # incident (at most one dump per request — a breaker trip
                # in the same request already dumped this ring).
                trace_mod.mark("ladder")
                metrics.FLIGHT.auto_dump(
                    "ladder", {"method": "stream_assign", "rung": rung}
                )
            return result, budget
        if method == "stream_reset":
            params = req.get("params") or {}
            sid = params.get("stream_id")
            with self._streams_lock:
                dropped = self._streams.pop(sid, None) is not None
                self._snapshots.pop(sid, None)
            if dropped:
                self._mark_churn()
                self._release_takeover(sid)
            return {"dropped": dropped}, None
        if method == "recommend":
            # The elasticity loop (utils/overload.recommend_payload):
            # per-stream consumer-count recommendations from the
            # lag-trend windows the stream path already records, plus
            # the current overload state — the external autoscaler
            # closes the loop on this.  params.stream_id (optional)
            # narrows to one stream; unknown ids simply return empty.
            params = req.get("params") or {}
            only = params.get("stream_id")
            horizon = params.get("horizon_s", 60.0)
            if isinstance(horizon, bool) or not isinstance(
                horizon, (int, float)
            ) or not 1.0 <= float(horizon) <= 86400.0:
                raise ValueError(
                    "params.horizon_s must be a number in [1, 86400]"
                )
            with self._streams_lock:
                items = list(self._streams.items())
            streams: Dict[str, Any] = {}
            for sid, st in items:
                if only is not None and sid != only:
                    continue
                # Snapshot without the stream lock: history is a
                # bounded deque (appends are GIL-atomic) and a torn
                # read here is a monitoring read, like any scrape.
                samples = list(st.history)
                streams[sid] = {
                    "slo_class": st.klass,
                    "consumers": len(st.members),
                    "partitions": (
                        int(st.pids.shape[0]) if st.pids is not None else 0
                    ),
                    "samples": samples,
                }
            return recommend_payload(
                streams, self._overload.snapshot(),
                horizon_s=float(horizon),
            ), None
        if method == "stream_flight":
            # One stream's private flight ring, dumped (and optionally
            # cleared) on demand — the global 256-record ring stays the
            # aggregate; this answers "what happened to THIS tenant"
            # without the other streams' records crowding the window.
            params = req.get("params") or {}
            sid = params.get("stream_id")
            with self._streams_lock:
                st = self._streams.get(sid)
                ring = st.flight if st is not None else None
            if ring is None:
                raise ValueError(f"unknown stream {sid!r}")
            records = ring.snapshot()  # redacted copies, oldest first
            cleared = bool(params.get("clear", False))
            if cleared:
                ring.clear()
            return {
                "stream_id": sid,
                "records": records,
                "cleared": cleared,
            }, None
        if method == "peer_sync":
            # Peer-coordination surface (federated/; DEPLOYMENT.md
            # "Federated assignment"): answer a peer's dual-exchange
            # round over this sidecar's registered local lag shard.
            # Every response is built by the audited federated/wire
            # serializer — consumer-axis aggregates only, never raw
            # lags (lint L019 confines construction there).
            if self._federation is None:
                raise ValueError(
                    "federation is not configured on this sidecar"
                )
            return self._federation.serve_sync(
                req.get("params") or {}
            ), None
        if method == "federation":
            # Operator surface: peer link states (breaker, last
            # outcome, epoch/fence ledger), the degradation rung, and
            # the last-good dual cache's age.
            if self._federation is None:
                return {"enabled": False}, None
            out = self._federation.status()
            out["enabled"] = True
            return out, None
        if method == "federated_assign":
            params = req.get("params") or {}
            klass = self._slo.resolve(None, params.get("slo_class"))
            self._reject_if_draining(klass)
            budget = _DeadlineBudget(
                self._slo.budget_s(klass, self._watchdog.timeout_s),
                clock=self._clock,
            )
            result = self._federated_assign(params, budget, klass)
            rung = result["federation"]["rung"]
            metrics.REGISTRY.counter(
                "klba_ladder_rung_total",
                {"method": "federated_assign", "rung": rung},
            ).inc()
            if rung != "global":
                trace_mod.mark("ladder")
                metrics.FLIGHT.auto_dump(
                    "ladder",
                    {"method": "federated_assign", "rung": rung},
                )
            return result, budget
        raise ValueError(f"unknown method {method!r}")

    def _stream_assign(
        self,
        params: Dict[str, Any],
        budget: Optional[_DeadlineBudget] = None,
        klass: str = "standard",
    ) -> Dict[str, Any]:
        import numpy as np

        if budget is None:
            budget = _DeadlineBudget(self._watchdog.timeout_s)

        sid = params.get("stream_id")
        if not isinstance(sid, str) or not sid:
            raise ValueError("params.stream_id must be a non-empty string")
        topic = params.get("topic", "t0")
        rows = _decode_wire_lags(params)
        delta_params = params.get("lag_delta")
        members = params.get("members") or []
        if not isinstance(members, list) or not members:
            raise ValueError("params.members must be a non-empty list")
        members_sorted = sorted(str(m) for m in members)
        if len(set(members_sorted)) != len(members_sorted):
            raise ValueError("params.members contains duplicates")
        C = len(members_sorted)
        opts = _validate_stream_options(params.get("options") or {})
        ack = _parse_assign_ack(params)
        resp_enc = _parse_accept_encoding(params)

        if delta_params is not None and rows:
            raise ValueError(
                "params.lags and params.lag_delta are mutually exclusive"
            )
        if delta_params is not None:
            # Sparse epoch (module docstring "Delta epochs"): only type
            # validation here — the delta applies against the stream's
            # stored base under its lock, inside the admitted path.
            delta = _parse_lag_delta(delta_params)
            lags = None
            pids_sorted = None
        else:
            delta = None
            # Shared validation with federated_assign (_parse_lag_rows):
            # non-negative lags (every kernel documents lags >= 0 as a
            # precondition and the reference's lag formula clamps at 0,
            # LagBasedPartitionAssignor.java:376-404), unique pids,
            # ascending-pid row order.
            pids_sorted, lags = _parse_lag_rows(rows)

        # Overload admission: shared with federated_assign (see
        # _admit_solve_work) — the shed ladder decides this request's
        # fate BEFORE any solver state is touched; the degrade rung's
        # meaning stays with each surface.
        decision = self._admit_solve_work(klass, stream_id=sid)

        with self._inflight(klass):
            return self._stream_assign_admitted(
                params, budget, klass, decision,
                sid, topic, lags, pids_sorted, members_sorted, C, opts,
                delta=delta, ack=ack, resp_enc=resp_enc,
            )

    @contextmanager
    def _inflight(self, klass: str):
        """The weighted in-flight depth bracket both solve surfaces
        share: add this request's class weight, feed the controller
        the new depth, and ALWAYS release on exit."""
        weight = CLASS_WEIGHTS.get(klass, 1.0)
        with self._inflight_lock:
            self._inflight_weight += weight
            depth = self._inflight_weight
        self._overload.note_depth(depth)
        try:
            yield
        finally:
            with self._inflight_lock:
                self._inflight_weight -= weight

    def _admit_solve_work(
        self, klass: str, stream_id: Optional[str] = None
    ):
        """THE overload admission both solve surfaces share
        (``stream_assign`` and ``federated_assign``): feed the CURRENT
        in-flight depth before deciding (rejected requests return
        before the post-admission accounting, so without this feed an
        all-shed class mix would freeze the depth EWMA at its stampede
        peak and the ladder could never step down — livelock), expire
        takeover-warming shares, decide FAIL-OPEN (the shed.decide
        fault point — or a genuine controller bug — must never take
        healthy traffic down), apply the per-class admission window
        scales, and raise the structured reject.  Returns the decision
        (None when the decision path failed open); what the "degrade"
        action means stays with each caller — the cheap answer differs
        per surface."""
        with self._inflight_lock:
            depth_now = self._inflight_weight
        self._overload.note_depth(depth_now)
        self._expire_takeover_warming()
        decision = None
        try:
            decision = self._overload.admission(klass)
        except Exception:
            LOGGER.warning(
                "overload admission decision failed; failing open "
                "(admit)", exc_info=True,
            )
        if decision is not None:
            if self._coalescer is not None:
                # Rung 1+ shrinks the megabatch admission window PER
                # CLASS — best_effort waves go small first, the
                # critical window stays wide (ROADMAP overload (b)).
                self._coalescer.set_window_scales(decision.window_scales)
            if decision.action == "reject":
                self._overload.note_shed(
                    klass, decision.rung_name, "rejected",
                    stream_id=stream_id,
                )
                raise ShedReject(
                    klass, decision.rung_name, decision.retry_after_ms
                )
        return decision

    def _stream_assign_admitted(
        self, params, budget, klass, decision,
        sid, topic, lags, pids_sorted, members_sorted, C, opts,
        delta=None, ack=None, resp_enc=None,
    ) -> Dict[str, Any]:
        """The admitted remainder of a stream_assign: stream state,
        the solve (or the degrade rung's kept_previous), the ladder."""
        import numpy as np

        created = False
        while True:
            with self._streams_lock:
                st = self._streams.get(sid)
                if st is None:
                    if len(self._streams) >= MAX_STREAMS:
                        raise ValueError(
                            f"too many live streams (max {MAX_STREAMS}); "
                            "stream_reset unused ones"
                        )
                    st = self._streams[sid] = _Stream()
                    created = True
            st.lock.acquire()
            # The stream may have been POISONED (solve failure) or reset
            # while this request waited on its lock — solving on the
            # orphaned engine would race the very abandoned thread the
            # poison quarantines.  Re-validate registration under the
            # lock; on a mismatch, loop and start over on fresh state.
            with self._streams_lock:
                if self._streams.get(sid) is st:
                    break
            st.lock.release()
        if created:
            # Roster churn: a new tenant's warm state should reach the
            # snapshot ahead of the periodic cadence (debounced).
            self._mark_churn()

        pace_held = False
        try:
            warm_restart = False
            if delta is not None:
                # Apply the sparse delta against the stream's stored
                # base, under its lock.  Any reason it cannot apply —
                # stale/duplicate/gapped base_epoch, unknown partition
                # ids (the roster moved), or no dense base at all
                # (restart/poison/reset rebuilt the stream) — forces a
                # dense RE-SYNC: the previous assignment is served
                # unchanged with ``resync: true`` when one is
                # servable, else the request errors asking for full
                # lags; either way the client must send dense rows
                # next epoch (test-pinned).
                resolved = self._apply_wire_delta(st, delta)
                if isinstance(resolved, str):
                    trace_mod.mark("resync")
                    metrics.REGISTRY.counter(
                        "klba_delta_epochs_total", {"outcome": "resync"}
                    ).inc()
                    base = st.last_lags
                    prev = (
                        st.engine._prev_choice
                        if st.engine is not None else None
                    )
                    # Servable only for the UNCHANGED roster: this
                    # early return runs before the membership-remap
                    # block, so serving prev onto a changed member
                    # list would misattribute every partition (and a
                    # changed roster invalidates the kept choice
                    # anyway — orphans need the repair pass).
                    servable = (
                        prev is not None
                        and st.members == members_sorted
                        and st.pids is not None
                        and st.pids.shape[0] == prev.shape[0]
                        and _keepable(prev, prev.shape[0], C)
                    )
                    if not servable:
                        if created and st.engine is None:
                            # Don't leave an engine-less husk holding a
                            # MAX_STREAMS slot: this stream was minted
                            # by a delta that cannot seed it.
                            with self._streams_lock:
                                if self._streams.get(sid) is st:
                                    self._streams.pop(sid)
                        raise ValueError(
                            f"params.lag_delta cannot apply "
                            f"({resolved}); resync: resend full "
                            "params.lags"
                        )
                    LOGGER.warning(
                        "stream %r lag_delta forced a resync (%s); "
                        "serving the previous assignment", sid, resolved,
                    )
                    # With the base lags gone (restart recovery holds
                    # choice + pids but never lag vectors), the served
                    # stats are NEUTRAL (zero lags -> quality 1.0) —
                    # one flagged resync epoch per stream beats an
                    # error storm undercutting the restarts-are-a-
                    # non-event contract.
                    stats_lags = (
                        base if base is not None
                        else np.zeros(prev.shape[0], dtype=np.int64)
                    )
                    choice, s = _serve_previous(prev, stats_lags, C)
                    a_delta, a_epoch = self._note_assignment(
                        st, ack, topic, members_sorted, st.pids, choice
                    )
                    return self._stream_result(
                        topic, members_sorted, st.pids, choice, s,
                        fallback_used=False, degraded_rung="none",
                        warm_restart=False, opts=opts, klass=klass,
                        shed=None, lag_epoch=st.lag_epoch, resync=True,
                        assign_delta=a_delta, assign_epoch=a_epoch,
                        resp_enc=resp_enc,
                    )
                lags, pids_sorted = resolved
            if st.engine is None:
                # Requested options are applied by the SAME update
                # block every epoch uses, so each default lives in
                # exactly one place.  Each stream gets its own small
                # flight ring alongside the engine.
                st.flight = _stream_ring()
                st.engine = _fresh_engine(C, st.flight, self._delta_opts, self._mesh)
                st.members = members_sorted
                # Poisoned-stream recovery: if the last epoch for this sid
                # died on the snake rung, warm-restart from the snapshot of
                # what the clients were actually handed (repair + bounded
                # refine) instead of paying a full cold solve.  A stale
                # snapshot (membership or pid set moved on) is discarded.
                with self._streams_lock:
                    snap = self._snapshots.pop(sid, None)
                if snap is not None:
                    snap_members, snap_pids, snap_choice = snap
                    if snap_members == members_sorted and np.array_equal(
                        snap_pids, pids_sorted
                    ):
                        st.engine.seed_choice(snap_choice)
                        st.pids = snap_pids
                        warm_restart = True
            elif st.recovered and (
                st.members != members_sorted
                or st.pids is None
                or st.pids.shape[0] != pids_sorted.shape[0]
                or not np.array_equal(st.pids, pids_sorted)
            ):
                # Recovered-stream drift guard (DEPLOYMENT.md "Restarts
                # and recovery"): the snapshot predates whatever moved
                # this roster, so remapping it would carry STALE state
                # into a membership change the process never observed —
                # discard THIS stream's warm state only (cold start);
                # every other recovered stream keeps its seed.
                LOGGER.warning(
                    "recovered stream %r arrived with a drifted "
                    "roster; discarding its snapshot state (cold "
                    "start)", sid,
                )
                # Rebuild, don't reset: the recovered engine is sized
                # for the snapshot's consumer count — a reset() would
                # cold-solve the NEW roster over the OLD C (imbalanced
                # counts on growth, an index past members_sorted on
                # shrink).  The stream keeps its flight ring.
                st.engine = _fresh_engine(C, st.flight, self._delta_opts, self._mesh)
                st.members = members_sorted
                st.pids = None
                metrics.REGISTRY.counter(
                    "klba_recovery_streams_total",
                    {"outcome": "discarded_drift"},
                ).inc()
                self._mark_churn()
            elif st.members != members_sorted:
                # Membership change: remap by NAME so survivors keep their
                # partitions (the engine's repair pass re-seats only
                # orphans/overflow next rebalance).
                new_rank = {m: i for i, m in enumerate(members_sorted)}
                old_to_new = np.fromiter(
                    (new_rank.get(m, -1) for m in st.members),
                    np.int32, count=len(st.members),
                )
                st.engine.remap_members(old_to_new, C)
                st.members = members_sorted
                self._mark_churn()
            # A different partition-id set at the SAME count would silently
            # misbind warm rows to new pids — force a cold solve (a count
            # change already does, via the engine's shape check).
            if st.pids is not None and not np.array_equal(
                st.pids, pids_sorted
            ):
                st.engine.reset()
            st.pids = pids_sorted
            if st.recovered:
                # First post-restart epoch on INTACT recovered state:
                # surfaced as a warm restart (same wire field as the
                # poisoned-snapshot recovery) so the restart stampede
                # is visible per stream; a drift-discarded stream
                # reports a plain cold start instead.  The standing-
                # pressure share is NOT released here: the epoch has
                # not run yet, and a fail-fast outcome (breaker open,
                # budget spent) would leave the device state cold —
                # the release rides the SUCCESS path below, so the
                # hold genuinely lasts until the warming dispatch
                # landed.
                warm_restart = st.engine._prev_choice is not None
                st.recovered = False
            _apply_stream_opts(st.engine, opts)

            fallback_used = False
            degraded_rung = "none"
            shed_info: Optional[Dict[str, Any]] = None
            prev = st.engine._prev_choice
            if (
                decision is not None
                and decision.action == "degrade"
                and _keepable(prev, lags.shape[0], C)
            ):
                # Shed ladder (degrade rung): serve the PREVIOUS
                # assignment — zero churn, zero device work, warm
                # state untouched.  Nothing failed, so this is not a
                # fallback and not a ladder descent; the shed itself
                # is the record.  A stream with no servable previous
                # choice (cold) is admitted instead — there is
                # nothing cheaper to serve it.
                choice, s = _serve_previous(prev, lags, C)
                self._overload.note_shed(
                    klass, decision.rung_name, "kept_previous",
                    stream_id=sid,
                )
                shed_info = {
                    "rung": decision.rung_name,
                    "served": "kept_previous",
                }
                self._note_epoch(st, klass, lags)
                a_delta, a_epoch = self._note_assignment(
                    st, ack, topic, members_sorted, pids_sorted, choice
                )
                return self._stream_result(
                    topic, members_sorted, pids_sorted, choice, s,
                    fallback_used=False, degraded_rung="none",
                    warm_restart=warm_restart, opts=opts, klass=klass,
                    shed=shed_info, lag_epoch=st.lag_epoch,
                    assign_delta=a_delta, assign_epoch=a_epoch,
                    resp_enc=resp_enc,
                )
            # Multi-tenant routing: with MORE than one live stream the
            # warm dispatch goes through the megabatch coalescer (one
            # vmapped device dispatch serves every concurrent epoch in
            # the shape bucket); a lone stream keeps the inline fast
            # path so single-tenant p50 is untouched.
            coalescer = self._coalescer
            if coalescer is not None:
                with self._streams_lock:
                    if len(self._streams) <= 1:
                        coalescer = None
            # Resync pacing (module docstring of _ResyncPacer): an
            # epoch that must rebuild its device state with a dense
            # full-vector upload (the post-restart first epoch, a
            # churn-invalidated resident) takes a bounded rebuild
            # slot; a restart wave then trickles through the device
            # instead of serializing it behind one dense mega-wave.
            if (
                self._resync_pacer is not None
                and getattr(st.engine, "needs_dense_resync", False)
            ):
                pace_held = self._resync_pacer.acquire(
                    budget.remaining()
                )
            try:
                # Ladder rung 1: the warm-resident engine, under the
                # stream breaker with the request's REMAINING budget.
                if coalescer is not None:
                    # The submission's admission deadline: the
                    # request's remaining budget, translated into the
                    # coalescer's (registry) clock domain — the flush
                    # triages rows whose class budget cannot survive a
                    # full wave.
                    rem = budget.remaining()
                    deadline_at = (
                        metrics.REGISTRY.clock() + rem
                        if rem is not None else None
                    )
                    choice = self._watchdog.call(
                        st.engine.submit_epoch, lags, coalescer,
                        key="stream", timeout_s=budget.remaining(),
                        budget_total_s=budget.total_s,
                        slo_class=klass, rank=class_rank(klass),
                        deadline_at=deadline_at,
                    )
                else:
                    choice = self._watchdog.call(
                        st.engine.rebalance, lags, key="stream",
                        timeout_s=budget.remaining(),
                        budget_total_s=budget.total_s,
                    )
                s = st.engine.last_stats
                # An adopted stream's WARMING dispatch succeeded: its
                # takeover share releases now (ROADMAP lifecycle (e)).
                # Steady state pays one empty-dict check.
                if self._takeover_warming:
                    self._release_takeover(sid)
                # Strike forgiveness (utils/scrub): only a RUN of
                # clean epochs clears the quarantine strikes —
                # escalation targets devices corrupting state faster
                # than the heal path restores it, and a flip-flop
                # serves one clean healing epoch between detections.
                st.clean_epochs += 1
                if (
                    st.scrub_strikes
                    and st.clean_epochs >= scrub_lib.FORGIVE_AFTER
                ):
                    st.scrub_strikes = 0
            except SolveRejected as rej:
                # FAIL-FAST rejection (breaker open / probe in flight /
                # budget spent): nothing ever ran, so the warm engine is
                # untouched and still valid — an open shared breaker must
                # NOT destroy every stream's warm state.  Degrade
                # host-side for this request only: keep serving the
                # previous assignment (zero churn) when it is directly
                # servable, else deal the snake and SEED the engine with
                # it so the stream state matches what the clients now run.
                # A DeadlineShed is the same fail-fast contract arriving
                # from the coalescer's admission triage (the row's class
                # budget expired while parked) — but it is a shed, not a
                # failure: when the previous assignment is servable, the
                # request is answered as a SHED (klba_shed_total was
                # already counted by the coalescer) without touching the
                # fallback/ladder incident accounting — a routine
                # overload shed must not burn the flight-recorder dump
                # budget or inflate the series operators page on.
                from .ops.coalesce import DeadlineShed

                if isinstance(rej, scrub_lib.CorruptStateDetected):
                    # A resident-state integrity check failed mid-
                    # request (per-epoch digest or a megabatch row
                    # check): the engine already quarantined itself —
                    # host truth intact, corrupt buffer never served —
                    # so this request degrades below and the NEXT epoch
                    # heals bit-exact.  Count the strike (repeats
                    # escalate to the stream breaker).
                    self._note_quarantine(sid, st, rej.buffers)
                deadline_shed = isinstance(rej, DeadlineShed)
                if deadline_shed and _keepable(prev, lags.shape[0], C):
                    choice, s = _serve_previous(prev, lags, C)
                    shed_info = {
                        "rung": "admit_deadline",
                        "served": "kept_previous",
                    }
                else:
                    if not self._host_fallback:
                        raise
                    LOGGER.warning(
                        "stream %r solve rejected without running; "
                        "keeping warm state and answering host-side",
                        sid, exc_info=True,
                    )
                    fallback_used = True
                    if _keepable(prev, lags.shape[0], C):
                        choice, s = _serve_previous(prev, lags, C)
                        degraded_rung = "kept_previous"
                    else:
                        choice, s = _snake_fallback(lags, C, prev)
                        st.engine.seed_choice(np.asarray(choice))
                        degraded_rung = "host_snake"
                    if deadline_shed:
                        shed_info = {
                            "rung": "admit_deadline",
                            "served": degraded_rung,
                        }
            except Exception:
                # A watchdog-abandoned worker thread may STILL be running
                # the engine's rebalance and will mutate its warm state
                # later with no lock held — the stream must be POISONED
                # (dropped) so no future epoch touches the orphaned
                # engine.  The response then descends the degraded-mode
                # ladder (cold device -> host snake) within what is left
                # of the SAME deadline budget.
                with self._streams_lock:
                    self._streams.pop(sid, None)
                self._mark_churn()
                self._release_takeover(sid)
                if not self._host_fallback:
                    raise
                LOGGER.warning(
                    "stream %r warm solve failed; poisoning state and "
                    "descending the degraded-mode ladder",
                    sid, exc_info=True,
                )
                choice, s, degraded_rung, fallback_used = (
                    self._stream_degraded(
                        sid, lags, C, opts, prev, budget,
                        members_sorted, pids_sorted,
                    )
                )
            # Advance the delta base UNDER the stream lock: a
            # concurrent delta request validates base_epoch against
            # last_lags inside this same lock, so an unlocked
            # two-field update here could let it read a matched epoch
            # with the successor's lag vector (a silently wrong base).
            self._note_epoch(st, klass, lags)
            lag_epoch_out = st.lag_epoch
            # Assignment-delta bookkeeping must also happen INSIDE the
            # locked region: the served base pair (assign_epoch,
            # last_served) must never tear against a concurrent
            # request's ack validation.
            a_delta, a_epoch = self._note_assignment(
                st, ack, topic, members_sorted, pids_sorted, choice
            )
        finally:
            if pace_held:
                self._resync_pacer.release()
            st.lock.release()

        return self._stream_result(
            topic, members_sorted, pids_sorted, choice, s,
            fallback_used=fallback_used, degraded_rung=degraded_rung,
            warm_restart=warm_restart, opts=opts, klass=klass,
            shed=shed_info, lag_epoch=lag_epoch_out,
            assign_delta=a_delta, assign_epoch=a_epoch,
            resp_enc=resp_enc,
        )

    def _note_epoch(self, st: _Stream, klass: str, lags) -> None:
        """Record one served epoch's elasticity sample: (time, total
        lag) into the stream's bounded trend window, plus its effective
        class — the raw material of ``{"method": "recommend"}`` — and
        advance the stream's delta base: ``lags`` becomes the vector a
        ``lag_delta`` with the NEW ``lag_epoch`` applies to.  Caller
        holds ``st.lock`` (the base pair must never tear against
        :meth:`_apply_wire_delta`'s locked read)."""
        st.klass = klass
        st.history.append(
            (self._clock(), int(lags.sum(dtype="int64")))
        )
        st.last_lags = lags
        st.lag_epoch += 1

    def _note_assignment(
        self, st: _Stream, ack, topic, members_sorted, pids_sorted,
        choice,
    ):
        """Advance the stream's assignment-delta base and decide this
        answer's encoding (module docstring "Delta responses").  Caller
        holds ``st.lock`` — the (epoch, last_served) pair must never
        tear against a concurrent request's ack validation, exactly
        like the lag base in :meth:`_note_epoch`.

        Returns ``(assignment_delta or None, new assign_epoch)``.  The
        delta is served only when the client's ack names the CURRENT
        epoch AND the roster (members + pid set) is unchanged — the
        same monotone-epoch/ack/resync ladder as the round-13 upload
        path; every other case answers dense, which re-seeds the
        client's base.  Outcomes mirror the upload counter:
        ``klba_assign_delta_epochs_total{outcome}``."""
        import numpy as np

        choice = np.asarray(choice, dtype=np.int32)
        pids = np.asarray(pids_sorted, dtype=np.int64)
        prev = st.last_served
        delta_out = None
        if ack is not None:
            servable = (
                prev is not None
                and ack == st.assign_epoch
                and prev[0] == list(members_sorted)
                and prev[1].shape == pids.shape
                and np.array_equal(prev[1], pids)
                and prev[2].shape == choice.shape
            )
            if servable:
                changed = np.flatnonzero(prev[2] != choice)
                delta_out = {
                    "base_epoch": st.assign_epoch,
                    "epoch": st.assign_epoch + 1,
                    "topic": topic,
                    "indices": pids[changed].tolist(),
                    # Owner = index into the (sorted) member list the
                    # client sent — stable exactly because the delta is
                    # only served on an unchanged roster.
                    "owners": choice[changed].tolist(),
                }
                outcome = "applied"
            elif prev is None or ack != st.assign_epoch:
                # Epoch gap / restart-rebuilt stream: the dense answer
                # below IS the resync.
                outcome = "resync"
            else:
                outcome = "fallback"
            metrics.REGISTRY.counter(
                "klba_assign_delta_epochs_total", {"outcome": outcome}
            ).inc()
        st.assign_epoch += 1
        st.last_served = (
            list(members_sorted), pids.copy(), choice.copy()
        )
        return delta_out, st.assign_epoch

    def _apply_wire_delta(self, st: _Stream, delta):
        """Apply a parsed ``lag_delta`` to the stream's stored base
        (caller holds ``st.lock``).  Returns ``(lags, pids_sorted)`` on
        success, or a human-readable REASON string when the delta
        cannot apply and the stream must re-sync dense."""
        import numpy as np

        d_pids, d_vals, base = delta
        if st.last_lags is None or st.pids is None:
            return "no dense base held for this stream"
        if base != st.lag_epoch:
            return (
                f"base_epoch {base} does not match the stream's "
                f"current lag_epoch {st.lag_epoch}"
            )
        pos = np.searchsorted(st.pids, d_pids)
        pos = np.clip(pos, 0, max(st.pids.shape[0] - 1, 0))
        if d_pids.size and not np.array_equal(st.pids[pos], d_pids):
            return "delta names partition ids outside the stream's set"
        lags = st.last_lags.copy()
        lags[pos] = d_vals
        return lags, st.pids

    def _stream_result(
        self, topic, members_sorted, pids_sorted, choice, s, *,
        fallback_used: bool, degraded_rung: str, warm_restart: bool,
        opts: Dict[str, Any], klass: str,
        shed: Optional[Dict[str, Any]],
        lag_epoch: int = 0, resync: bool = False,
        assign_delta: Optional[Dict[str, Any]] = None,
        assign_epoch: int = 0,
        resp_enc: Optional[str] = None,
    ) -> Dict[str, Any]:
        import numpy as np

        if assign_delta is not None:
            # Delta-encoded answer (module docstring "Delta responses"):
            # only the changed rows cross the wire — the O(P) dense
            # dict is never even BUILT host-side, so the response cost
            # scales with churn in both directions.
            out: Dict[str, Any] = {"assignment_delta": assign_delta}
        else:
            choice_l = np.asarray(choice).tolist()
            pids_l = pids_sorted.tolist()
            assignments: Dict[str, List[List[Any]]] = {
                m: [] for m in members_sorted
            }
            for row, consumer in enumerate(choice_l):
                assignments[members_sorted[consumer]].append(
                    [topic, pids_l[row]]
                )
            out = _encode_dense_assignments(assignments, resp_enc)
        return {
            **out,
            "stream": {
                "cold_start": s.cold_start,
                "refined": s.refined,
                "guardrail_tripped": s.guardrail_tripped,
                "churn": s.churn,
                "repaired_rows": s.repaired_rows,
                "max_mean_imbalance": s.max_mean_imbalance,
                "imbalance_bound": s.imbalance_bound,
                "quality_ratio": s.quality_ratio,
                "count_spread": s.count_spread,
                "fallback_used": fallback_used,
                # Which ladder rung answered: none (warm engine) |
                # kept_previous (rejected without running; prior choice
                # served) | cold_device | host_snake — plus whether this
                # epoch warm-restarted from a poisoned-stream snapshot.
                "degraded_rung": degraded_rung,
                "warm_restart": warm_restart,
                # SLO surface: the request's effective class, and — when
                # the shed ladder (or the coalescer's deadline triage)
                # degraded it — which rung shed it and what was served.
                "slo_class": klass,
                "shed": shed,
                # Delta-epoch surface (module docstring "Delta epochs"):
                # the monotone base counter a lag_delta must name, and
                # whether THIS answer demands a dense re-send.
                "lag_epoch": lag_epoch,
                "resync": resync,
                # Delta-RESPONSE surface: the monotone epoch of the
                # assignment this answer carries — the value a client's
                # next ``params.assign_ack`` names to opt into a
                # delta-encoded answer.
                "assign_epoch": assign_epoch,
                # Adaptive-delta surface (ROADMAP delta follow-on (b)):
                # the delta/dense cutoff actually in force this epoch.
                "delta_effective_fraction": s.delta_effective_fraction,
                # Multi-device surface: this epoch's cold solve (if
                # any) ran on the P-sharded backend.
                "sharded_solve": s.sharded_solve,
            },
            "options": opts,
        }

    def _stream_degraded(
        self, sid, lags, C, opts, prev, budget, members_sorted, pids_sorted
    ):
        """Rungs 2-3 of the degraded-mode ladder, after the warm engine
        was poisoned: a COLD solve on a FRESH engine (never the orphaned
        one — its abandoned worker may still mutate it) within the
        remaining deadline budget, then the host-side snake LPT.  Returns
        ``(choice, stats, degraded_rung, fallback_used)``."""
        import numpy as np

        ring = _stream_ring()
        fresh = _fresh_engine(C, ring, self._delta_opts, self._mesh)
        _apply_stream_opts(fresh, opts)
        try:
            choice = self._watchdog.call(
                fresh.rebalance, lags, key="stream",
                timeout_s=budget.remaining(),
            )
        except Exception:
            # Rung 3: the snake answers from the host within microseconds
            # of remaining budget, and the choice the clients now run is
            # SNAPSHOTTED so the next epoch can warm-restart from it.
            LOGGER.warning(
                "stream %r cold retry failed; answering with host snake",
                sid, exc_info=True,
            )
            choice, s = _snake_fallback(lags, C, prev)
            with self._streams_lock:
                if len(self._snapshots) >= MAX_STREAMS:
                    self._snapshots.pop(next(iter(self._snapshots)))
                self._snapshots[sid] = (
                    list(members_sorted),
                    pids_sorted.copy(),
                    np.asarray(choice, dtype=np.int32),
                )
            self._mark_churn()
            return choice, s, "host_snake", True
        # The cold rung recovered: install the fresh engine as the
        # stream's new warm state (unless a concurrent request already
        # re-registered the sid — never clobber live state).
        with self._streams_lock:
            if sid not in self._streams and len(self._streams) < MAX_STREAMS:
                nst = _Stream()
                nst.engine = fresh
                nst.flight = ring
                nst.members = list(members_sorted)
                nst.pids = pids_sorted
                self._streams[sid] = nst
        self._mark_churn()
        return choice, fresh.last_stats, "cold_device", False

    # -- federated assignment (federated/; DEPLOYMENT.md) ------------------

    def _federation_fence_token(self) -> Optional[int]:
        """The fencing token stamped on peer-bound payloads: the
        snapshot writer lease's token when fencing is engaged, else
        None — one token fences both the snapshot writes AND the peer
        syncs of a replaced instance."""
        store = self._snapshot_store
        if store is None or not store.fencing_enabled:
            return None
        return store.lease_token

    def _federated_assign(
        self, params: Dict[str, Any], budget: _DeadlineBudget, klass: str
    ) -> Dict[str, Any]:
        """One federated epoch: register the local shard, run the
        dual-exchange rounds inside the remaining budget, and serve the
        LOCAL shard's slice of the converged global assignment — or
        degrade down the federation ladder, bottoming out at exactly
        the single-cluster stateless solve.  The request rides the same
        overload admission + weighted in-flight depth accounting as
        ``stream_assign``, so slow peer rounds feed the controller's
        pressure signals like any other long-running work."""
        if self._federation is None:
            raise ValueError(
                "federation is not configured on this sidecar"
            )
        topic = params.get("topic", "t0")
        members = params.get("members") or []
        if not isinstance(members, list) or not members:
            raise ValueError("params.members must be a non-empty list")
        members_sorted = sorted(str(m) for m in members)
        if len(set(members_sorted)) != len(members_sorted):
            raise ValueError("params.members contains duplicates")
        C = len(members_sorted)
        rows = _decode_wire_lags(params)
        pids_sorted, lags = _parse_lag_rows(rows)
        resp_enc = _parse_accept_encoding(params)

        # Overload admission, shared with stream_assign (the
        # "peer-round cost feeds the controller" contract); on THIS
        # surface a degrade skips the peer rounds entirely — the
        # local-only rung is the cheap answer, since no previous
        # choice exists to keep on a stateless solve.
        decision = self._admit_solve_work(klass)
        force_local = False
        if decision is not None and decision.action == "degrade":
            self._overload.note_shed(
                klass, decision.rung_name, "local_only"
            )
            force_local = True

        with self._inflight(klass):
            if force_local:
                fed = {
                    "rung": "local_only", "choice": None, "rounds": 0,
                    "peers_ok": 0, "staleness_s": None,
                    "converged": False,
                }
            else:
                fed = self._federation.assign(
                    lags, C, budget.remaining
                )
            if fed["choice"] is not None:
                choice = fed["choice"]
                s = _host_choice_stats(
                    choice, lags, C, None, cold_start=True
                )
                choice_l = list(choice)
                pids_l = pids_sorted.tolist()
                assignments: Dict[str, List[List[Any]]] = {
                    m: [] for m in members_sorted
                }
                for row, consumer in enumerate(choice_l):
                    assignments[members_sorted[int(consumer)]].append(
                        [topic, pids_l[row]]
                    )
                stats_out = {
                    "max_mean_imbalance": s.max_mean_imbalance,
                    "imbalance_bound": s.imbalance_bound,
                    "quality_ratio": s.quality_ratio,
                    "count_spread": s.count_spread,
                }
            else:
                # Rung local_only: today's single-cluster behavior,
                # unchanged — the stateless device solve with the host
                # greedy as its degraded rung, inside what is left of
                # the SAME deadline budget.
                rows_plain = [
                    [int(p), int(v)]
                    for p, v in zip(pids_sorted, lags)
                ]
                assignments, rb_stats = _solve(
                    {topic: rows_plain},
                    {m: [topic] for m in members_sorted},
                    "rounds",
                    watchdog=self._watchdog,
                    host_fallback=self._host_fallback,
                    deadline=budget,
                )
                stats_out = json.loads(rb_stats.to_json())
            fed_out = {
                "rung": fed["rung"],
                "rounds": fed["rounds"],
                "converged": fed["converged"],
                "peers_ok": fed["peers_ok"],
                "staleness_s": fed["staleness_s"],
                # True when the gossip daemon's warm dual cache served
                # this assign in one local round (no synchronous peer
                # RTT) — the bench's constant-time-serve gate reads it.
                "warm_cache": bool(fed.get("warm_cache", False)),
                "epoch": self._federation.local_epoch,
            }
            metrics.FLIGHT.record(
                "federation_assign",
                {
                    "rung": fed["rung"],
                    "rounds": fed["rounds"],
                    "converged": fed["converged"],
                    "num_partitions": int(lags.shape[0]),
                    "num_members": C,
                    "slo_class": klass,
                },
            )
            return {
                **_encode_dense_assignments(assignments, resp_enc),
                "federation": fed_out,
                "stats": stats_out,
            }

    # -- resident-state scrubbing (utils/scrub) ----------------------------

    def _scrub_targets(self) -> List[Tuple[str, Callable[[], str]]]:
        """The scrubber's audit jobs: one per live stream.  Each
        auditor takes the stream lock NON-blocking (idle streams only
        — the scrubber must never park behind a serving epoch), audits
        the full resident state against the host mirror, and
        quarantines on a mismatch."""
        with self._streams_lock:
            items = list(self._streams.items())
        return [
            (sid, lambda sid=sid, st=st: self._audit_stream(sid, st))
            for sid, st in items
        ]

    def _audit_stream(self, sid: str, st: _Stream) -> str:
        if not st.lock.acquire(blocking=False):
            return "busy"
        try:
            with self._streams_lock:
                if self._streams.get(sid) is not st:
                    return "skipped"  # reset/poisoned while we queued
            if st.engine is None:
                return "skipped"
            audited, fails = scrub_lib.audit_engine(st.engine)
            if not audited:
                return "skipped"
            if fails:
                for buffer in fails:
                    metrics.REGISTRY.counter(
                        "klba_scrub_failures_total", {"buffer": buffer}
                    ).inc()
                LOGGER.warning(
                    "scrub audit of stream %r FAILED (%s); "
                    "quarantining", sid, ",".join(fails),
                )
                st.engine.quarantine_resident(fails, source="scrub")
                self._note_quarantine(sid, st, fails)
            return "audited"
        finally:
            st.lock.release()

    def _note_quarantine(
        self, sid: str, st: _Stream, buffers: List[str]
    ) -> None:
        """Strike accounting for one quarantined stream (caller holds
        ``st.lock``): repeated failures escalate to the stream breaker
        (utils/watchdog.trip_breaker) — a single cosmic-ray flip
        heals silently, a device corrupting state faster than the heal
        path restores it gets sidelined."""
        st.clean_epochs = 0
        st.scrub_strikes += 1
        if st.scrub_strikes >= scrub_lib.ESCALATE_AFTER:
            # Direct trip (not a failure count): the healing epoch
            # between strikes succeeds and would reset a consecutive-
            # failure counter, so counting could never sideline the
            # corrupt/heal flip-flop this escalation targets.
            self._watchdog.trip_breaker("stream")
            scrub_lib.record_quarantine(
                buffers, "escalated", stream_id=sid, source="strikes"
            )

    def scrub_stats(self) -> Optional[Dict[str, Any]]:
        """The wire ``stats.scrub`` section (tools/dump_metrics.py
        --summary prints it next to the lifecycle rows)."""
        if self._scrubber is None:
            return None
        out = self._scrubber.stats()
        with self._streams_lock:
            items = list(self._streams.items())
        # Scrub-coverage SLO (ROADMAP state-integrity (b)): a scrubber
        # that stopped making audit progress WHILE streams are live is
        # wedged — flagged by presence here and in dump_metrics
        # --summary, not only visible as counters that stopped moving.
        out["wedged"] = bool(out.get("stalled")) and bool(items)
        quarantined = 0
        for _sid, st in items:
            engine = st.engine
            if engine is not None and getattr(
                engine, "quarantined", False
            ):
                quarantined += 1
        out["quarantined_streams"] = quarantined
        return out

    # -- takeover warming (ROADMAP lifecycle (e)) --------------------------

    def _release_takeover(self, sid: Any) -> None:
        """One adopted stream finished warming (first post-boot epoch
        served, reset, discarded, or poisoned): release its share of
        the standing takeover pressure so the admission window steps
        back to full scale exactly when the warm-up drains."""
        with self._streams_lock:
            weight = self._takeover_warming.pop(sid, None)
        if weight:
            self._overload.release_standing_pressure(weight)

    def _expire_takeover_warming(self) -> None:
        """TTL backstop, checked on the admission path (one dict-empty
        test per request while shares remain): shares whose streams
        never reconnected are released wholesale so one decommissioned
        consumer group in the snapshot cannot hold the admission
        window at rung-1 scale for the life of the process."""
        if not self._takeover_warming or (
            self._takeover_deadline is None
            or self._clock() < self._takeover_deadline
        ):
            return
        with self._streams_lock:
            stale, self._takeover_warming = (
                dict(self._takeover_warming), {}
            )
        total = sum(stale.values())
        if total:
            LOGGER.warning(
                "takeover warm-up TTL expired with %d stream(s) never "
                "seen (%s); releasing their standing pressure",
                len(stale), sorted(stale),
            )
            self._overload.release_standing_pressure(total)

    # -- lifecycle ---------------------------------------------------------

    def _set_lifecycle(self, state: str) -> None:
        with self._lifecycle_lock:
            self._lifecycle = state
        self._m_lifecycle.set(_LIFECYCLE_STATES.index(state))

    def _mark_churn(self) -> None:
        """Roster churn (stream joined/left/poisoned, membership
        moved): nudge the snapshot writer ahead of its cadence."""
        if self._snapshot_writer is not None:
            self._snapshot_writer.mark_churn()

    def _reject_if_draining(self, klass: str) -> None:
        """The drain's admission stop: new solve work gets a structured
        reject (same wire shape as an overload shed, rung
        ``"draining"``) with a retry hint sized to the drain window —
        the client's backoff naturally lands on the replacement
        instance.  Observability methods (ping/stats/metrics/flight)
        stay served so the drain itself remains watchable."""
        if self._lifecycle == "serving":
            return
        retry_ms = int(
            min(60_000.0, max(500.0, self._drain_timeout_s * 1000.0))
        )
        record_shed(klass, "draining", "rejected")
        raise DrainReject(klass, retry_ms)

    def _snapshot_sections(self) -> Dict[str, Any]:
        """Collect every host-recoverable section for utils/snapshot:
        per-stream ``{members, pids, choice, slo_class, lag-trend
        window}``, breaker states/cooldowns, the overload rung.  Lag
        trend times are stored as AGES relative to the write (the
        monotonic epoch dies with the process; ages rebase cleanly on
        load).  A stream mid-epoch (lock contended) is skipped this
        cadence rather than stalling the writer behind a device solve.
        """
        import numpy as np

        with self._streams_lock:
            items = list(self._streams.items())
        now = self._clock()
        streams: Dict[str, Any] = {}
        for sid, st in items:
            if not st.lock.acquire(timeout=0.5):
                continue  # mid-epoch; the next cadence catches it
            try:
                if st.engine is None or st.pids is None:
                    continue
                choice = st.engine.export_state()
                if choice is None or choice.shape[0] != st.pids.shape[0]:
                    continue
                P = int(st.pids.shape[0])
                dense = bool(np.array_equal(st.pids, np.arange(P)))
                streams[sid] = {
                    "members": list(st.members),
                    # Dense pid sets (the common case) compact to the
                    # count — a 100k-partition stream should not cost
                    # ~600 KB of JSON per snapshot for 0..P-1.
                    "pids": P if dense else [int(p) for p in st.pids],
                    "choice": [int(c) for c in choice],
                    "slo_class": st.klass,
                    "history": [
                        [max(0.0, now - t), int(lag)]
                        for t, lag in list(st.history)
                    ],
                }
            finally:
                st.lock.release()
        sections = {
            "streams": streams,
            "breakers": self._watchdog.export_state(),
            "overload": self._overload.export_state(),
        }
        if self._federation is not None:
            # Federation state must survive restarts: the monotone
            # local epoch (peers reject a regressed replacement as
            # stale), the per-peer ledger, and the last-good-global
            # duals — all fenced by the same writer tokens as every
            # other section (DEPLOYMENT.md "Federated assignment").
            sections["federation"] = self._federation.export_state()
        return sections

    def snapshot_now(self) -> Dict[str, Any]:
        """One synchronous snapshot write (operator action / drills);
        ``{"ok": False, "error": "snapshots disabled"}`` without a
        configured path."""
        if self._snapshot_writer is None:
            return {"ok": False, "error": "snapshots disabled"}
        return self._snapshot_writer.write_now()

    def _final_snapshot(self) -> None:
        """The drain's final write.  Unlike the periodic cadence —
        where a lock-contended stream is simply caught by the next tick
        — there IS no next tick here, and the atomic rename would
        replace a previous snapshot that still holds that stream's
        warm state with one that silently lacks it.  So any live
        stream the collector had to skip (a wedged solve the drain
        timed out on) carries its record FORWARD from the previous
        file instead of vanishing; the recovery-side staleness and
        drift guards already police how trustworthy that older record
        is."""
        try:
            sections = self._snapshot_sections()
            with self._streams_lock:
                live = set(self._streams)
            missing = live - set(sections.get("streams") or {})
            if missing:
                prev = self._snapshot_store.load()
                prev_streams = (
                    prev.sections.get("streams") or {}
                    if prev.sections else {}
                )
                carried = 0
                for sid in missing:
                    body = prev_streams.get(sid)
                    if body is not None:
                        sections["streams"][sid] = body
                        carried += 1
                LOGGER.warning(
                    "final snapshot: %d stream(s) still lock-held at "
                    "drain timeout; carried %d forward from the "
                    "previous snapshot", len(missing), carried,
                )
            self._snapshot_store.save(sections)
        except Exception:  # noqa: BLE001 — the drain must complete
            LOGGER.warning(
                "final snapshot collection failed; skipping the write",
                exc_info=True,
            )

    def lifecycle_stats(self) -> Dict[str, Any]:
        """The wire ``stats.lifecycle`` section (also printed by
        tools/dump_metrics.py --summary)."""
        out: Dict[str, Any] = {
            "state": self._lifecycle,
            "snapshot": (
                self._snapshot_store.stats()
                if self._snapshot_store is not None else None
            ),
            "recovery": self._last_recovery,
            # Cross-host hand-off surface: the writer lease (holder,
            # token, age) and the boot-time hand-off outcome — what
            # dump_metrics --summary prints for "who owns this state".
            "lease": (
                self._snapshot_store.lease_stats()
                if self._snapshot_store is not None else None
            ),
            "handoff": self._last_handoff,
        }
        return out

    def _acquire_writer_lease(self) -> None:
        """The boot side of the takeover protocol: acquire the fenced
        writer lease (fencing enabled) and record the hand-off outcome
        for the lifecycle surface.  Never raises; a failed acquisition
        serves with snapshot writes denied."""
        store = self._snapshot_store
        if store is None or not store.fencing_enabled:
            return
        res = store.acquire_lease(wait_s=self._lease_wait_s)
        mode = (
            "fresh" if res.get("previous_holder") is None
            else "takeover_crash" if res.get("previous_expired")
            else "takeover_drain"
        )
        self._last_handoff = {
            "acquired": bool(res.get("ok")),
            "mode": mode,
            "token": res.get("token"),
            "waited_ms": res.get("waited_ms"),
            "previous_holder": res.get("previous_holder"),
            "error": res.get("error"),
        }
        metrics.FLIGHT.record(
            "lifecycle", {"event": "handoff", **self._last_handoff}
        )
        LOGGER.warning(
            "writer lease %s (mode=%s, token=%s, waited %.0f ms, "
            "previous holder %r)",
            "acquired" if res.get("ok") else "NOT acquired", mode,
            res.get("token"), res.get("waited_ms") or 0.0,
            res.get("previous_holder"),
        )

    def _prestack_recovered(self) -> None:
        """Rebuild each recovered stream's device-resident warm state
        from its seeded choice (zero-lag table build — choice
        unchanged, bit-exactness intact), off the serving path.
        Best-effort per stream: a failed pre-stack leaves that stream
        on the inline dense-rebuild path it would have taken anyway."""
        with self._streams_lock:
            items = list(self._streams.items())
        built = 0
        for sid, st in items:
            if not st.lock.acquire(timeout=5.0):
                continue
            try:
                if st.recovered and st.engine is not None:
                    if st.engine.prestack_resident():
                        built += 1
            except Exception:  # noqa: BLE001 — per-stream best effort
                LOGGER.warning(
                    "pre-stack of recovered stream %r failed; it will "
                    "rebuild inline on its first epoch",
                    sid, exc_info=True,
                )
            finally:
                st.lock.release()
        if built:
            metrics.REGISTRY.counter(
                "klba_recovery_prestacked_total"
            ).inc(built)
            if self._last_recovery is not None:
                self._last_recovery["streams_prestacked"] = built
        LOGGER.info(
            "pre-stacked %d/%d recovered stream(s)", built, len(items)
        )

    def _recover(self) -> None:
        """Boot-time warm-restart recovery (called by :meth:`start`
        BEFORE the warm-up and the accept loop): load the snapshot
        fail-open, restore breaker/overload state, and rehydrate each
        stream via ``seed_choice`` — staleness guards per the module
        docstring.  Never raises; the worst outcome is a counted cold
        start."""
        import numpy as np

        t0 = metrics.REGISTRY.clock()
        load = self._snapshot_store.load()
        info: Dict[str, Any] = {
            "outcome": load.outcome,
            "age_s": load.age_s,
            "sections_skipped": list(load.skipped),
            "streams_recovered": 0,
            "streams_discarded": 0,
        }
        stale = (
            load.age_s is not None
            and load.age_s > self._snapshot_max_age_s
        )
        if stale and load.outcome in ("ok", "partial"):
            # Whole-file staleness guard: rosters and lag trends older
            # than the max age are misinformation — cold start, loudly.
            LOGGER.warning(
                "snapshot is %.0fs old (> max age %.0fs); rehydrating "
                "nothing", load.age_s, self._snapshot_max_age_s,
            )
            info["outcome"] = "stale"
        elif load.sections:
            breakers = load.sections.get("breakers")
            if breakers is not None:
                self._watchdog.restore_state(breakers)
            overload = load.sections.get("overload")
            if overload is not None:
                self._overload.restore_state(overload)
            federation = load.sections.get("federation")
            if federation is not None and self._federation is not None:
                self._federation.restore_state(federation)
            recovered, discarded, weight = self._rehydrate_streams(
                load.sections.get("streams") or {}, np
            )
            info["streams_recovered"] = recovered
            info["streams_discarded"] = discarded
            if recovered:
                # Recovery-aware shed ladder (ROADMAP lifecycle (c)):
                # every recovered stream will fire its next epoch at
                # once — seed the depth EWMA with that stampede's
                # weighted depth NOW, so a restart under live overload
                # re-escalates on the FIRST admission decision instead
                # of waiting one evaluation interval while the queue
                # melts.  The EWMA decays through the normal hysteresis
                # if the stampede never materializes.
                self._overload.seed_recovery_depth(weight)
                info["seeded_depth"] = weight
                # Lease-aware shedding during the takeover window
                # (ROADMAP lifecycle (e)): the recovered streams'
                # class weight also parks as STANDING pressure — the
                # depth EWMA above decays with traffic, but a
                # replacement serving cold streams must hold the
                # admission window at rung-1 scale until every
                # adopted stream actually finished warming, or the
                # takeover stampede coalesces into giant cold waves.
                self._overload.add_standing_pressure(weight)
                self._takeover_deadline = (
                    self._clock() + TAKEOVER_WARMING_TTL_S
                )
                info["standing_pressure"] = weight
        info["duration_ms"] = (metrics.REGISTRY.clock() - t0) * 1000.0
        self._last_recovery = info
        metrics.REGISTRY.gauge("klba_recovery_duration_ms").set(
            info["duration_ms"]
        )
        metrics.FLIGHT.record("lifecycle", {"event": "recovery", **info})
        LOGGER.info(
            "recovery: outcome=%s streams_recovered=%d discarded=%d "
            "in %.1f ms", info["outcome"], info["streams_recovered"],
            info["streams_discarded"], info["duration_ms"],
        )

    def _rehydrate_streams(
        self, bodies: Dict[str, Any], np
    ) -> Tuple[int, int, float]:
        """Seed one engine per snapshot stream; a malformed or
        unservable stream record is discarded ALONE (counted), never an
        exception into the boot path.  Returns ``(recovered,
        discarded, weighted_depth)`` — the weight sum (CLASS_WEIGHTS
        over the recovered streams' classes) seeds the overload
        controller's depth EWMA for the restart stampede."""
        recovered = discarded = 0
        weight = 0.0
        m_rec = metrics.REGISTRY.counter(
            "klba_recovery_streams_total", {"outcome": "recovered"}
        )
        m_disc = metrics.REGISTRY.counter(
            "klba_recovery_streams_total", {"outcome": "discarded"}
        )
        now = self._clock()
        for sid, body in dict(bodies).items():
            try:
                members = sorted(str(m) for m in body["members"])
                if not members or len(set(members)) != len(members):
                    raise ValueError("bad member roster")
                C = len(members)
                pids_raw = body["pids"]
                pids = (
                    np.arange(int(pids_raw), dtype=np.int64)
                    if isinstance(pids_raw, int)
                    else np.asarray(
                        [int(p) for p in pids_raw], dtype=np.int64
                    )
                )
                choice = np.asarray(
                    [int(c) for c in body["choice"]], dtype=np.int32
                )
                if (
                    choice.shape[0] != pids.shape[0]
                    or not _keepable(choice, choice.shape[0], C)
                ):
                    raise ValueError("choice not servable for roster")
                klass = body.get("slo_class", "standard")
                if klass not in SLO_CLASSES:
                    klass = "standard"
                st = _Stream()
                st.flight = _stream_ring()
                st.engine = _fresh_engine(C, st.flight, self._delta_opts, self._mesh)
                # The recovery contract: the first warm epoch must be
                # bit-identical to an uninterrupted process's epoch
                # from the SAME seeded choice — seed_choice leaves
                # device state stale, so both sides rebuild their
                # tables from this host vector deterministically.
                st.engine.seed_choice(choice)
                st.members = members
                st.pids = pids
                st.klass = klass
                st.recovered = True
                for age, lag in body.get("history") or []:
                    st.history.append(
                        (now - float(age), int(lag))
                    )
                with self._streams_lock:
                    if len(self._streams) >= MAX_STREAMS:
                        raise ValueError("stream cap reached")
                    self._streams[str(sid)] = st
                    # Takeover-warming ledger (ROADMAP lifecycle (e)):
                    # this stream's class weight stays parked as
                    # standing pressure until its first post-boot
                    # epoch serves (released per stream).
                    self._takeover_warming[str(sid)] = (
                        CLASS_WEIGHTS.get(klass, 1.0)
                    )
                self._recovery_shapes.append((int(pids.shape[0]), C))
                recovered += 1
                weight += CLASS_WEIGHTS.get(klass, 1.0)
                m_rec.inc()
            except Exception:  # noqa: BLE001 — discard THIS stream only
                LOGGER.warning(
                    "discarding unrecoverable snapshot stream %r",
                    sid, exc_info=True,
                )
                discarded += 1
                m_disc.inc()
        return recovered, discarded, weight

    def begin_drain(self) -> bool:
        """Initiate a graceful drain (idempotent): stop admissions,
        then — on the drain thread — wait out in-flight requests,
        flush the coalescer's waves, write the final snapshot, and
        close the listener.  Returns False when already draining or
        stopped."""
        with self._lifecycle_lock:
            if self._lifecycle != "serving":
                return False
            self._lifecycle = "draining"
        self._m_lifecycle.set(_LIFECYCLE_STATES.index("draining"))
        if self._snapshot_writer is not None:
            # Stop the cadence; the drain worker owns the final write.
            self._snapshot_writer.close()
        metrics.FLIGHT.record("lifecycle", {"event": "drain"})
        LOGGER.warning(
            "drain initiated: admissions stopped, flushing in-flight "
            "work (timeout %.1fs)", self._drain_timeout_s,
        )
        self._drain_thread = threading.Thread(
            target=self._drain_worker, name="klba-drain", daemon=True
        )
        self._drain_thread.start()
        return True

    def _drain_worker(self) -> None:
        deadline = self._clock() + self._drain_timeout_s
        # 1. In-flight requests: every admitted request finishes (or
        #    the timeout fires — a wedged solve must not hold the
        #    drain past its window; its watchdog abandons it anyway).
        with self._active_cond:
            while self._active_requests > 0:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    LOGGER.warning(
                        "drain timeout with %d request(s) in flight; "
                        "proceeding", self._active_requests,
                    )
                    break
                self._active_cond.wait(min(0.05, remaining))
        # 2. Coalescer: flush the parked waves and their readbacks so
        #    no future is abandoned mid-wave.  Fault point drain.flush
        #    fires inside; a failure is logged and the drain proceeds —
        #    a broken flush must never block the final snapshot.
        if self._coalescer is not None:
            try:
                quiet = self._coalescer.drain(
                    timeout_s=max(0.0, deadline - self._clock())
                )
                if not quiet:
                    LOGGER.warning(
                        "coalescer did not quiesce within the drain "
                        "window; proceeding"
                    )
            except Exception:  # noqa: BLE001 — drain must complete
                LOGGER.warning(
                    "coalescer drain failed; proceeding with the final "
                    "snapshot", exc_info=True,
                )
        # 3. Final snapshot: the state the restart rehydrates from
        #    (merge-aware: a lock-held stream keeps its previous
        #    record instead of vanishing from the file).  The writer
        #    lease is released AFTER it lands, so a replacement
        #    adopts instantly (drain-initiated hand-off) instead of
        #    waiting out the TTL; a crash (stop()) never releases —
        #    the TTL expiry is what fences a dead holder.
        if self._snapshot_writer is not None:
            self._final_snapshot()
        if self._snapshot_store is not None:
            self._snapshot_store.release_lease()
        # 4. Close the listener; the process may now exit.
        self._close_listener()
        if self._coalescer is not None:
            self._coalescer.close()
        self._set_lifecycle("stopped")
        metrics.FLIGHT.record("lifecycle", {"event": "drained"})
        LOGGER.warning("drain complete: listener closed")
        self._stopped_event.set()

    def wait_stopped(self, timeout_s: Optional[float] = None) -> bool:
        """Block until a drain (or stop) finished; True when it did."""
        return self._stopped_event.wait(timeout_s)

    def install_signal_handlers(self) -> None:
        """Graceful drain on SIGTERM/SIGINT (main-thread only — a
        Python signal-handler constraint).  The FIRST signal starts
        the drain; a second one (drain hung, operator insisting)
        force-stops without the final snapshot."""
        import signal

        def _handler(signum, frame):
            LOGGER.warning("signal %d: draining", signum)
            if not self.begin_drain():
                LOGGER.warning(
                    "signal %d during drain: forcing stop", signum
                )
                self.stop()

        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _handler)

    def _close_listener(self) -> None:
        with self._lifecycle_lock:
            if self._listener_closed:
                return
            self._listener_closed = True
        if self._thread is not None:
            self._tcp.shutdown()
        self._tcp.server_close()
        if self._scrubber is not None:
            self._scrubber.close()
        if self._metrics_http is not None:
            self._metrics_http.stop()
            self._metrics_http = None
        if self._federation is not None:
            self._federation.close()

    def start(self) -> "AssignorService":
        # Process-wide telemetry hooks, BEFORE the warm-up builds the
        # executables of interest: the compile counter must see them,
        # and request-thread log lines carry the minted request id.
        install_compile_counter()
        metrics.install_log_request_ids()
        # Quality-plane knobs installed process-wide BEFORE the mesh
        # configure and the warm-up: the per-mode warm-up jobs (and
        # every quality solve after them) route through
        # ops/dispatch.resolve_quality_mode, which must already see
        # this instance's configuration.
        from .ops import dispatch as dispatch_mod

        dispatch_mod.set_quality_mode(self._quality_mode)
        dispatch_mod.set_quality_tile(self._quality_tile)
        if self._mesh is not None:
            # Mesh discovery/validation ONCE at service start (never
            # per request), and BEFORE the warm-up below: with the
            # manager active, the warm-up's megabatch waves lock onto
            # the stream-sharded placement and the sharded executables
            # compile off the serving path.  A spec the visible
            # devices cannot satisfy degrades to single-device here —
            # boot keeps serving.
            from .sharded import mesh as mesh_mod

            self._mesh.configure()
            mesh_mod.activate(self._mesh)
        if self._snapshot_store is not None:
            # Takeover handshake FIRST (DEPLOYMENT.md "Cross-host
            # hand-off"): acquire the writer lease — waiting out a
            # crashed predecessor's TTL, or adopting instantly after a
            # drain released it — so the fencing epoch turns over
            # BEFORE the state is read.  From this point every stale
            # write from the predecessor is rejected by the backend.
            # Fail-open: an unacquirable lease still boots (writes
            # denied, serving untouched).
            self._acquire_writer_lease()
            # Warm-restart recovery BEFORE the warm-up and the accept
            # loop: rehydrated streams contribute their shapes to the
            # warm-up below, so the restart stampede's first warm
            # epochs compile nothing (the restart_storm bench gate).
            self._recover()
            if self._recovery_prestack:
                # Pre-stack recovered rosters (ROADMAP lifecycle (b)):
                # rebuild each recovered engine's device-resident
                # state off the serving path so the storm's first
                # epochs dispatch like steady-state (coalescible)
                # warm traffic instead of inline dense table-builds.
                self._prestack_recovered()
        coalesce_batch = (
            self._coalescer.max_batch if self._coalescer is not None else 1
        )
        if self._warmup_shapes:
            # Pre-compile before serving: connections arriving meanwhile
            # queue in the TCP backlog and are answered once warm.
            from .warmup import warmup

            for max_p, consumers, topics in self._warmup_shapes:
                warmup(
                    max_partitions=max_p,
                    consumers=[consumers],
                    topics=[topics],
                    solvers=self._warmup_solvers,
                    # Megabatch coverage: with coalescing enabled, one
                    # synthetic multi-stream wave per batch-pow2 bucket
                    # compiles the re-stack AND locked executables off
                    # the serving path; the delta ladder warms with the
                    # service's configured rung count.
                    coalesce_max_batch=coalesce_batch,
                    delta_buckets=self._warm_delta_buckets,
                    mesh_manager=self._mesh,
                )
        if self._recovery_shapes and self._recovery_warmup:
            # Megabatch warm-up for the RECOVERED shapes, off the
            # serving path: only the stream engine's executables (cold
            # chain, fused warm build/resident, and — multi-tenant —
            # the megabatch pair per batch bucket); the stateless
            # solvers warm via warmup_shapes as before.
            from .warmup import warmup

            for max_p, consumers in sorted(set(self._recovery_shapes)):
                warmup(
                    max_partitions=max_p,
                    consumers=[consumers],
                    solvers=("stream",),
                    coalesce_max_batch=coalesce_batch,
                    delta_buckets=self._warm_delta_buckets,
                    mesh_manager=self._mesh,
                )
        # The serving surfaces come up under the lifecycle lock: a
        # drain/stop that raced the (possibly minutes-long) recovery
        # warm-up — SIGTERM mid-deploy, with install_signal_handlers()
        # armed before start() — has already closed the TCP socket, and
        # spawning serve_forever on it (or resurrecting the metrics
        # listener on a stopped instance) would crash the accept thread
        # and present a service that can never answer.  _close_listener
        # flips ``_listener_closed`` under this same lock, so exactly
        # one side wins.
        with self._lifecycle_lock:
            if self._lifecycle != "serving" or self._listener_closed:
                LOGGER.warning(
                    "start() aborted: drain/stop arrived during "
                    "recovery/warm-up; not opening the listener"
                )
                return self
            if self._snapshot_writer is not None:
                self._snapshot_writer.start()
            if self._scrubber is not None:
                self._scrubber.start()
            if self._metrics_port is not None:
                from .utils.metrics_http import MetricsHTTPServer

                self._metrics_http = MetricsHTTPServer(
                    self.address[0], self._metrics_port
                ).start()
            self._thread = threading.Thread(
                target=self._tcp.serve_forever, name="klba-service",
                daemon=True,
            )
            self._thread.start()
        LOGGER.info("assignor service listening on %s:%d", *self.address)
        return self

    @property
    def metrics_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) of the HTTP /metrics listener, None if disabled
        or not yet started."""
        if self._metrics_http is None:
            return None
        return self._metrics_http.address

    def stop(self) -> None:
        """Immediate stop WITHOUT a drain: no admission wind-down and
        no FINAL snapshot (the file holds whatever the periodic
        cadence last wrote — the crash-equivalent contract the restart
        drills rely on).  Use :meth:`begin_drain` for the graceful
        path; stop() after a completed drain is a no-op."""
        if self._snapshot_writer is not None:
            self._snapshot_writer.close()
        self._close_listener()
        if self._coalescer is not None:
            self._coalescer.close()
        if self._mesh is not None:
            # Uninstall OUR manager only (a replacement instance's mesh
            # must not be clobbered by a stopping predecessor).
            from .sharded import mesh as mesh_mod

            mesh_mod.deactivate(self._mesh)
        self._set_lifecycle("stopped")
        self._stopped_event.set()

    def __enter__(self) -> "AssignorService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class AssignorServiceClient:
    """Blocking line-protocol client (what the JVM plugin side implements)."""

    # Methods the reconnect-once policy must NOT auto-resend: they mutate
    # server-side warm state, so a request that timed out mid-response may
    # already have been applied.  (assign/ping/stats are stateless;
    # stream_reset re-applied is a no-op.)
    NON_IDEMPOTENT_METHODS = frozenset({"stream_assign"})

    def __init__(self, host: str, port: int, timeout_s: float = 60.0):
        self._host = host
        self._port = port
        self._timeout_s = timeout_s
        self._next_id = 0
        self._lock = threading.Lock()
        # Reconnect-once events, visible to the embedding shim: a timeout
        # or connection drop mid-request leaves the socket in an undefined
        # state (a late half-response would desynchronize every subsequent
        # request), so the socket is closed and rebuilt, never reused.
        self.reconnects = 0
        # Trace id echoed by the LAST response envelope (success, shed,
        # or error) — the client-side pivot from a wire outcome to the
        # sidecar's kept trace (``{"method": "trace"}``).
        self.last_trace_id: Optional[str] = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout_s
        )
        self._file = self._sock.makefile("rwb")

    def _close_quietly(self) -> None:
        # Each close gets its own guard: a flush error closing the dead
        # file must not leak the underlying socket fd.
        for close in (self._file.close, self._sock.close):
            try:
                close()
            except OSError:
                pass  # already torn down — the rebuild is the point

    def _round_trip(self, payload: bytes) -> bytes:
        self._file.write(payload)
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("service closed the connection")
        return line

    def request(self, method: str, params: Optional[Dict] = None) -> Any:
        # Client echo of the causal context: a client calling from
        # inside an active scope (the shim's lag-read trace, a peer
        # coordinator's request scope) propagates it on the wire, so
        # the sidecar's segment joins the caller's trace instead of
        # rooting a new one.
        traceparent = metrics.current_traceparent()
        with self._lock:
            self._next_id += 1
            req = {"id": self._next_id, "method": method}
            if params is not None:
                req["params"] = params
            if traceparent is not None:
                req["traceparent"] = traceparent
            payload = json.dumps(req).encode() + b"\n"
            if self._file.closed:
                # A previous request's reconnect died inside _connect()
                # (e.g. sidecar restarting): rebuild before sending so one
                # failed recovery cannot brick the client forever.  Does
                # not consume THIS request's single retry.
                self._connect()
                self.reconnects += 1
            try:
                line = self._round_trip(payload)
            except OSError as exc:
                # socket.timeout / ConnectionError / peer drop: the socket
                # is in an undefined state — close and reconnect ONCE.
                # Only IDEMPOTENT methods are resent: a stream_assign may
                # already have executed server-side (a timeout mid-solve),
                # and re-executing it would advance the warm state twice
                # behind the client's back.  For those the caller gets a
                # ConnectionError and decides (the JVM shim falls back to
                # its built-in greedy).  A second failure propagates.
                LOGGER.warning(
                    "request failed (%s: %s); reconnecting once",
                    type(exc).__name__, exc,
                )
                self._close_quietly()
                self._connect()
                self.reconnects += 1
                if method in self.NON_IDEMPOTENT_METHODS:
                    raise ConnectionError(
                        f"connection failed mid-{method}; the request may "
                        "or may not have been applied server-side — not "
                        "resending a non-idempotent method (the connection "
                        "has been rebuilt for subsequent requests)"
                    ) from exc
                line = self._round_trip(payload)
        resp = json.loads(line)
        self.last_trace_id = resp.get("trace_id")
        if "error" in resp:
            shed = resp["error"].get("shed")
            if shed is not None:
                # Rebuild the typed rejection so callers implement the
                # backoff contract from fields, not by parsing the
                # human-readable message.
                exc = ShedReject(
                    shed["class"], shed["rung"],
                    int(shed["retry_after_ms"]),
                )
                exc.trace_id = resp.get("trace_id")
                raise exc
            raise RuntimeError(resp["error"]["message"])
        result = resp["result"]
        if isinstance(result, dict) and "assignments_encoded" in result:
            # Transparent inflate of a compressed dense response
            # (accept_encoding opt-in): callers keep reading the plain
            # ``assignments`` key either way.
            result = decode_wire_assignments(result)
        return result

    def ping(self) -> bool:
        return self.request("ping") == "pong"

    def assign(
        self,
        topics: Dict[str, List[Tuple[int, int]]],
        subscriptions: Dict[str, List[str]],
        solver: str = "rounds",
    ) -> Dict[str, List[Tuple[str, int]]]:
        result = self.request(
            "assign",
            {
                "topics": topics,
                "subscriptions": subscriptions,
                "solver": solver,
            },
        )
        return {
            m: [(t, int(p)) for t, p in tps]
            for m, tps in result["assignments"].items()
        }

    def stream_assign(
        self,
        stream_id: str,
        topic: str,
        lags: Optional[List[Tuple[int, int]]],
        members: List[str],
        options: Optional[Dict[str, Any]] = None,
        lag_delta: Optional[Dict[str, Any]] = None,
        encoding: Optional[str] = None,
    ) -> Dict[str, Any]:
        """One warm-start epoch; returns the raw result dict
        (``assignments`` + ``stream`` stats).  Pass ``lag_delta``
        (and ``lags=None``) to send a sparse delta epoch — see the
        module docstring "Delta epochs" and
        :class:`..lag.LagDeltaTracker`, which produces both shapes
        from consecutive lag reads.  ``encoding="zlib"`` compresses a
        DENSE lag payload on the wire (the post-restart resync storm's
        full-vector re-sends shrink ~5-10x); a server that does not
        know the encoding answers a structured error and the request
        falls back to plain JSON transparently."""
        params: Dict[str, Any] = {
            "stream_id": stream_id,
            "topic": topic,
            "members": members,
        }
        if lags is not None:
            if encoding == "zlib":
                params["lags"] = encode_lags_zlib(lags)
                params["encoding"] = "zlib"
            else:
                params["lags"] = lags
        if lag_delta is not None:
            params["lag_delta"] = lag_delta
        if options is not None:
            params["options"] = options
        try:
            return self.request("stream_assign", params)
        except ShedReject:
            # A shed is the server's decision, not an encoding
            # problem — resending plain would just double the load the
            # ladder is shedding.
            raise
        except RuntimeError:
            if params.get("encoding") is None:
                raise
            # Fallback to plain JSON: a round-16+ server answers
            # "unknown encoding" for encodings it lacks, and a server
            # PREDATING params.encoding fails parsing the base64
            # string with some other ValueError — either way the one
            # recovery is an uncompressed resend (a genuine non-
            # encoding error simply re-raises identically from the
            # plain attempt, one extra round trip on an already-failed
            # epoch).
            params.pop("encoding")
            params["lags"] = lags
            return self.request("stream_assign", params)

    def federated_assign(
        self,
        topic: str,
        lags: List[Tuple[int, int]],
        members: List[str],
        slo_class: Optional[str] = None,
    ) -> Dict[str, Any]:
        """One federated epoch (DEPLOYMENT.md "Federated assignment"):
        the server converges a global assignment with its peers and
        answers its LOCAL shard's slice; the ``federation`` section
        reports the degradation rung actually served."""
        params: Dict[str, Any] = {
            "topic": topic, "lags": lags, "members": members,
        }
        if slo_class is not None:
            params["slo_class"] = slo_class
        return self.request("federated_assign", params)

    def federation(self) -> Dict[str, Any]:
        """The federation operator surface (peer links, rung, cache)."""
        return self.request("federation")

    def stream_reset(self, stream_id: str) -> bool:
        return self.request("stream_reset", {"stream_id": stream_id})[
            "dropped"
        ]

    def close(self) -> None:
        self._file.close()
        self._sock.close()

    def __enter__(self) -> "AssignorServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def main() -> None:
    """``python -m kafka_lag_based_assignor_tpu.service [host] [port]
    [--warmup P:C[,P:C...]]``

    ``--warmup`` pre-compiles the listed (max_partitions : num_consumers)
    shapes for the default device solvers before the service starts
    answering — a production sidecar should always pass its expected
    shapes here so a default-configuration rebalance never pays a
    first-compile (unwarmed solver/shape/option combinations still
    compile on demand).  Unknown flags are an error, not silently
    ignored.
    """
    import argparse

    logging.basicConfig(level=logging.INFO)

    def warmup_spec(text: str):
        from .utils.config import parse_warmup_shapes

        try:
            return parse_warmup_shapes(text)
        except ValueError as exc:
            raise argparse.ArgumentTypeError(str(exc))

    parser = argparse.ArgumentParser(
        prog="kafka_lag_based_assignor_tpu.service",
        description="TPU assignor sidecar (newline-JSON over TCP)",
    )
    parser.add_argument("host", nargs="?", default="127.0.0.1")
    parser.add_argument("port", nargs="?", type=int, default=7531)
    parser.add_argument(
        "--warmup", type=warmup_spec, default=None,
        metavar="P:C[:T][,P:C[:T]...]",
        help="pre-compile these (max_partitions:num_consumers[:topics]) "
             "shapes before serving",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve the Prometheus text exposition over plain HTTP on "
             "this port (GET /metrics); omit to disable",
    )
    parser.add_argument(
        "--coalesce-window-ms", type=float, default=0.5, metavar="MS",
        help="megabatch admission window for concurrent stream epochs "
             "(default 0.5 ms)",
    )
    parser.add_argument(
        "--coalesce-max-batch", type=int, default=32, metavar="N",
        help="max stream epochs per megabatch flush; <= 1 disables "
             "cross-stream coalescing (default 32)",
    )
    parser.add_argument(
        "--coalesce-lock-waves", type=int, default=1, metavar="N",
        help="consecutive identical-stream-set waves before a shape "
             "group's roster locks onto the device-resident fast path "
             "(default 1)",
    )
    parser.add_argument(
        "--coalesce-serial", action="store_true",
        help="disable the double-buffered flush pipeline (strict-"
             "serial upload/dispatch/readback per wave)",
    )
    parser.add_argument(
        "--no-delta", action="store_true",
        help="disable delta epochs (sparse lag updates onto the "
             "device-resident lag buffer; every upload stays dense)",
    )
    parser.add_argument(
        "--delta-max-fraction", type=float, default=0.125,
        metavar="FRAC",
        help="changed-partition fraction above which a warm epoch "
             "uploads dense instead of a delta (default 0.125)",
    )
    parser.add_argument(
        "--delta-buckets", type=int, default=6, metavar="N",
        help="pow2 K-ladder rungs for delta uploads (16..16<<N-1; each "
             "rung is one warmed executable per shape; default 6)",
    )
    parser.add_argument(
        "--snapshot-path", default=None, metavar="FILE",
        help="crash-safe lifecycle snapshot file (atomic writes); "
             "enables warm-restart recovery at boot; omit to disable",
    )
    parser.add_argument(
        "--snapshot-interval-ms", type=float, default=30_000.0,
        metavar="MS",
        help="periodic snapshot cadence (churn writes happen sooner; "
             "default 30000)",
    )
    parser.add_argument(
        "--snapshot-max-age-ms", type=float, default=900_000.0,
        metavar="MS",
        help="boot-time staleness guard: an older snapshot rehydrates "
             "nothing (default 900000)",
    )
    parser.add_argument(
        "--drain-timeout-ms", type=float, default=10_000.0, metavar="MS",
        help="graceful-drain window for in-flight requests and "
             "coalescer waves (default 10000)",
    )
    parser.add_argument(
        "--snapshot-backend", default="file",
        choices=["file", "memory", "object"], metavar="KIND",
        help="where the snapshot lives: 'file' (per-instance local "
             "file), 'memory', or 'object' (object-store-shaped, "
             "versioned CAS — enables cross-host hand-off; the path "
             "is then the store directory)",
    )
    parser.add_argument(
        "--snapshot-lease-ttl-ms", type=float, default=0.0,
        metavar="MS",
        help="epoch-fenced writer lease TTL; > 0 engages fencing "
             "(boot acquires the lease, saves carry its token, a "
             "fenced-off predecessor's writes are rejected); 0 "
             "disables (default)",
    )
    parser.add_argument(
        "--snapshot-lease-wait-ms", type=float, default=0.0,
        metavar="MS",
        help="how long boot waits for a crashed predecessor's lease "
             "to expire before serving WITHOUT it (writes denied); "
             "0 = auto (2x ttl + 1s)",
    )
    parser.add_argument(
        "--resync-max-inflight", type=int, default=8, metavar="N",
        help="cap on concurrent post-restart dense resync rebuilds "
             "(excess epochs wait, counted klba_resync_paced_total); "
             "0 disables pacing (default 8)",
    )
    parser.add_argument(
        "--scrub-interval-ms", type=float, default=30_000.0,
        metavar="MS",
        help="resident-state scrubber cadence (background audit of "
             "device buffers vs host truth; quarantine + bit-exact "
             "heal on mismatch); <= 0 disables (default 30000)",
    )
    parser.add_argument(
        "--federation-self-id", default=None, metavar="ID",
        help="this sidecar's stable federation peer id (enables the "
             "federated assignment plane; DEPLOYMENT.md 'Federated "
             "assignment')",
    )
    parser.add_argument(
        "--federation-peers", default=None, metavar="ID=HOST:PORT,...",
        help="peer sidecars for federated assignment "
             "('id=host:port,id=host:port'); requires "
             "--federation-self-id",
    )
    parser.add_argument(
        "--federation-rounds", type=int, default=16, metavar="N",
        help="max dual-exchange rounds per federated_assign "
             "(default 16)",
    )
    parser.add_argument(
        "--federation-sync-timeout-ms", type=float, default=2_000.0,
        metavar="MS",
        help="per-peer sync RPC deadline (also bounded by the request "
             "budget; default 2000)",
    )
    parser.add_argument(
        "--federation-max-staleness-ms", type=float, default=300_000.0,
        metavar="MS",
        help="how old the last-good-global dual cache may be and "
             "still serve the middle federation rung (default 300000)",
    )
    parser.add_argument(
        "--federation-gossip-interval-ms", type=float, default=0.0,
        metavar="MS",
        help="cadence of the background dual-gossip daemon (0 = off; "
             "> 0 serves federated_assign from the warm dual cache in "
             "one local round)",
    )
    parser.add_argument(
        "--recovery-prestack", action="store_true",
        help="pre-stack recovered rosters at boot (device-resident "
             "rebuild off the serving path) so the restart storm's "
             "first epochs coalesce like steady-state traffic",
    )
    parser.add_argument(
        "--mesh-devices", default="off", metavar="SPEC",
        help="device mesh for the sharded backends: 'off' (default, "
             "single-device), 'auto' (all visible devices), or a "
             "device count; discovered/validated once at start "
             "(DEPLOYMENT.md 'Multi-device sharding')",
    )
    parser.add_argument(
        "--mesh-solve-min-rows", type=int, default=65536, metavar="N",
        help="partition floor below which the P-sharded solve backend "
             "is not selected (single device wins outright; default "
             "65536)",
    )
    parser.add_argument(
        "--mesh-shape", default="off", metavar="SxD",
        help="cross-axis ('streams','p') factorization of the mesh "
             "pool: 'off' (default, 1-D rungs), 'auto' (most square "
             "split favouring 'p'), or 'SxD' (e.g. '2x4'); faults "
             "degrade 2-D -> streams -> p -> single (DEPLOYMENT.md "
             "'Cross-axis mesh')",
    )
    parser.add_argument(
        "--quality-mode", default="auto",
        choices=("sinkhorn", "linear", "auto"),
        help="quality-solve routing (DEPLOYMENT.md 'Quality modes'): "
             "dense sinkhorn, the linear-space O(P + C) mirror-prox "
             "path, or auto (linear at scale / under a mesh; default)",
    )
    parser.add_argument(
        "--quality-tile", type=int, default=1024, metavar="ROWS",
        help="linear quality mode's streamed tile size in rows (pow2; "
             "peak device memory O(tile*C + P + C); default 1024)",
    )
    parser.add_argument(
        "--federation-capacity", default=None, metavar="W,W,...",
        help="this cluster's per-consumer capacity weight vector "
             "(comma-separated positive floats) for the weighted "
             "federated count marginal; unset = uniform",
    )
    opts = parser.parse_args()
    federation_capacity = (
        [float(v) for v in opts.federation_capacity.split(",")]
        if opts.federation_capacity else None
    )
    service = AssignorService(
        opts.host, opts.port, warmup_shapes=opts.warmup,
        coalesce_window_ms=opts.coalesce_window_ms,
        coalesce_max_batch=opts.coalesce_max_batch,
        coalesce_lock_waves=opts.coalesce_lock_waves,
        coalesce_pipeline=not opts.coalesce_serial,
        delta_enabled=not opts.no_delta,
        delta_max_fraction=opts.delta_max_fraction,
        delta_buckets=opts.delta_buckets,
        metrics_port=opts.metrics_port,
        snapshot_path=opts.snapshot_path,
        snapshot_interval_s=max(opts.snapshot_interval_ms, 1.0) / 1000.0,
        snapshot_max_age_s=max(opts.snapshot_max_age_ms, 1.0) / 1000.0,
        drain_timeout_s=max(opts.drain_timeout_ms, 0.0) / 1000.0,
        snapshot_backend=opts.snapshot_backend,
        snapshot_lease_ttl_s=max(opts.snapshot_lease_ttl_ms, 0.0)
        / 1000.0,
        snapshot_lease_wait_s=max(opts.snapshot_lease_wait_ms, 0.0)
        / 1000.0,
        resync_max_inflight=opts.resync_max_inflight,
        recovery_prestack=opts.recovery_prestack,
        scrub_interval_ms=opts.scrub_interval_ms,
        federation_self_id=opts.federation_self_id,
        federation_peers=opts.federation_peers,
        federation_rounds=opts.federation_rounds,
        # No silent clamp: a non-positive timeout fails the boot (the
        # coordinator validates), like the config-key path — a 1 ms
        # floor would time out every exchange and present a sidecar
        # that "works" but never federates.
        federation_sync_timeout_s=opts.federation_sync_timeout_ms
        / 1000.0,
        federation_max_staleness_s=max(
            opts.federation_max_staleness_ms, 0.0
        ) / 1000.0,
        federation_gossip_interval_s=max(
            opts.federation_gossip_interval_ms, 0.0
        ) / 1000.0,
        federation_capacity=federation_capacity,
        mesh_devices=opts.mesh_devices,
        mesh_solve_min_rows=opts.mesh_solve_min_rows,
        mesh_shape=opts.mesh_shape,
        quality_mode=opts.quality_mode,
        quality_tile=opts.quality_tile,
    )
    # SIGTERM/SIGINT drain gracefully: admissions stop with a
    # structured retry-after reject, in-flight waves flush, the final
    # snapshot lands, the listener closes — a deploy is a non-event
    # (DEPLOYMENT.md "Restarts and recovery").
    service.install_signal_handlers()
    service.start()
    print(f"listening on {service.address[0]}:{service.address[1]}", flush=True)
    service.wait_stopped()


if __name__ == "__main__":
    main()
