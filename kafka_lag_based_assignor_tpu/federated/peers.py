"""Peer coordination: dual-exchange rounds, breakers, staleness, ladder.

One :class:`FederationCoordinator` lives in each sidecar and plays both
protocol roles:

* **server** — :meth:`serve_sync` answers a peer's ``peer_sync``
  request over THIS sidecar's registered local lag shard: handshake
  scalars (``phase: hello``) or the shard's marginal contribution
  under the carried duals (``phase: exchange``).  Stateless per round
  (the duals ride in the request), so concurrent initiators never
  conflict.  Monotone **epoch** and **fencing-token** checks run per
  sender: a request whose epoch or token regresses below the recorded
  maximum is answered with a structured reject and counted
  (``klba_peer_stale_duals_total``) — stale or fenced state is dropped,
  never averaged in.
* **initiator** — :meth:`assign` converges a GLOBAL assignment for the
  local shard inside the request's deadline budget: a hello round fixes
  the shared scale/cap from every peer's scalars, then synchronized
  exchange rounds sum the per-shard marginals and step the shared
  duals (:mod:`..ops.fedsolve`) until convergence, and the local shard
  is rounded with the other shards' converged loads as a fixed base.
  Every per-peer exchange runs under that peer's circuit breaker
  (utils/watchdog, key ``peer:<id>``) with a bounded per-call timeout,
  through a reconnect-once line client.

Degradation ladder (``FEDERATION_RUNGS``): any incomplete round —
partitioned peer, tripped breaker, stale/fenced response, exhausted
budget — abandons the exchange and falls to the **last-good-global**
duals (bounded staleness: the cache serves only within
``max_staleness_s`` and for the same consumer count), then to
**local_only**, where the caller runs today's single-cluster solve
untouched — a fully partitioned peer set fails open to exactly the
pre-federation behavior.

Fault points (utils/faults): ``peer.partition`` / ``peer.slow_link``
fire at the link transport, ``peer.sync`` inside the breaker-wrapped
exchange, ``peer.stale_duals`` in the initiator's response validation
(a firing plan makes the response count as stale).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import numpy as np

from ..utils import faults, metrics
from ..utils.watchdog import Watchdog
from . import wire

LOGGER = logging.getLogger(__name__)

#: The degradation ladder, best to worst (gauge exports the index).
FEDERATION_RUNGS = ("global", "last_good_global", "local_only")

#: Default bound on exchange rounds per assign (each round is one
#: marginal RPC per peer; convergence typically lands well under it —
#: the leader's damped iteration exits in ~6-24 steps).
DEFAULT_MAX_ROUNDS = 16

#: Default per-peer sync RPC timeout (seconds) — small relative to any
#: request budget: a slow link must cost one bounded wait, not the
#: whole deadline.
DEFAULT_SYNC_TIMEOUT_S = 2.0

#: Default bounded staleness of the last-good-global dual cache.
DEFAULT_MAX_STALENESS_S = 300.0


class PeerSpec(NamedTuple):
    peer_id: str
    host: str
    port: int


def parse_peer_specs(text: str) -> List[PeerSpec]:
    """Parse ``"id=host:port,id=host:port"`` (the config/CLI grammar);
    raises ValueError on malformed or duplicate entries."""
    specs: List[PeerSpec] = []
    seen = set()
    for entry in str(text).split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry or ":" not in entry.split("=", 1)[1]:
            raise ValueError(
                f"peer spec {entry!r} must be 'id=host:port'"
            )
        pid, addr = entry.split("=", 1)
        host, port_s = addr.rsplit(":", 1)
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(f"peer spec {entry!r} has a non-integer port")
        if not pid or not host or not 0 < port < 65536:
            raise ValueError(f"peer spec {entry!r} is invalid")
        if pid in seen:
            raise ValueError(f"duplicate peer id {pid!r}")
        seen.add(pid)
        specs.append(PeerSpec(pid, host, port))
    return specs


class PeerDropped(RuntimeError):
    """One peer's contribution failed for this round (transport,
    protocol reject, stale/fenced response): raised INSIDE the
    breaker-wrapped exchange so consecutive failures trip that peer's
    breaker, and caught by the round loop, which abandons the global
    attempt (partial marginal sums are never used)."""

    def __init__(self, peer_id: str, reason: str):
        super().__init__(f"peer {peer_id!r} dropped: {reason}")
        self.peer_id = peer_id
        self.reason = reason


class _PeerLink:
    """One peer's transport: a lazily built reconnect-once line client
    (the same :class:`..service.AssignorServiceClient` the JVM shim
    models) plus the per-sender monotone (epoch, fence) ledger."""

    def __init__(self, spec: PeerSpec, timeout_s: float):
        self.spec = spec
        self.timeout_s = float(timeout_s)
        self._client = None
        self._lock = threading.Lock()
        # Highest epoch / fencing token ever seen FROM this peer: a
        # response regressing below either is stale/fenced state from
        # a predecessor and is dropped, never averaged in.
        self.max_epoch_seen = -1
        self.max_fence_seen: Optional[int] = None
        self.last_outcome: Optional[str] = None

    def request(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """One ``peer_sync`` round trip.  Fault points: a
        ``peer.partition`` raise = unreachable peer; a
        ``peer.slow_link`` latency plan delays here (the caller's
        watchdog deadline bounds the damage)."""
        faults.fire("peer.partition")
        faults.fire("peer.slow_link")
        with self._lock:
            if self._client is None:
                from ..service import AssignorServiceClient

                self._client = AssignorServiceClient(
                    self.spec.host, self.spec.port,
                    timeout_s=self.timeout_s,
                )
            return self._client.request(
                wire.PEER_SYNC_METHOD, params
            )

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                try:
                    self._client.close()
                except OSError:
                    pass  # already torn down
                self._client = None


class FederationCoordinator:
    """Both halves of the federation protocol for one sidecar (module
    docstring).  ``watchdog`` hosts the per-peer breakers (keys
    ``peer:<id>`` — they surface in the service's ``stats.breakers``
    next to the solver breakers); ``fence_token`` is a zero-arg
    callable returning this sidecar's current writer fencing token
    (utils/snapshot lease) or None when fencing is off."""

    def __init__(
        self,
        self_id: str,
        peers: List[PeerSpec],
        watchdog: Optional[Watchdog] = None,
        max_rounds: int = DEFAULT_MAX_ROUNDS,
        sync_timeout_s: float = DEFAULT_SYNC_TIMEOUT_S,
        max_staleness_s: float = DEFAULT_MAX_STALENESS_S,
        fence_token: Optional[Callable[[], Optional[int]]] = None,
        clock: Optional[Callable[[], float]] = None,
        capacity: Optional[Any] = None,
        gossip_interval_s: float = 0.0,
        gossip_freshness_s: Optional[float] = None,
    ):
        if not self_id:
            raise ValueError("federation self_id must be non-empty")
        if float(gossip_interval_s) < 0:
            raise ValueError(
                f"gossip_interval_s={gossip_interval_s} must be >= 0"
            )
        if any(p.peer_id == self_id for p in peers):
            raise ValueError(
                f"peer list names this sidecar's own id {self_id!r}"
            )
        if int(max_rounds) < 1:
            raise ValueError(f"max_rounds={max_rounds} must be >= 1")
        if float(sync_timeout_s) <= 0:
            raise ValueError(
                f"sync_timeout_s={sync_timeout_s} must be > 0"
            )
        self.self_id = str(self_id)
        self.max_rounds = int(max_rounds)
        self.sync_timeout_s = float(sync_timeout_s)
        self.max_staleness_s = float(max_staleness_s)
        self._fence_token = fence_token or (lambda: None)
        self._clock = clock or metrics.REGISTRY.clock
        # Weighted shards (ROADMAP federated (c)): this cluster's
        # per-consumer capacity weight vector, exchanged in the hello
        # phase and summed into the global count-marginal target.  None
        # = contribute uniform weights (the n/C back-compat marginal
        # when NO shard advertises capacity).  Length is validated
        # against C at use — a roster-size change simply drops it.
        self.capacity = (
            np.asarray(capacity, dtype=np.float64)
            if capacity is not None else None
        )
        self._watchdog = watchdog or Watchdog(
            sync_timeout_s, cooldown_s=30.0, failure_threshold=2
        )
        self._links = {
            p.peer_id: _PeerLink(p, self.sync_timeout_s) for p in peers
        }
        # Local shard (the server side's truth) + the monotone local
        # epoch.  Guarded by one lock; serve_sync and assign both read
        # it.  The dedup cache is keyed by (epoch, scale) — one entry,
        # rebuilt when either moves.
        self._shard_lock = threading.Lock()
        self._shard: Optional[Dict[str, Any]] = None
        self.local_epoch = 0
        # Per-INITIATOR monotone (epoch, fence) ledger for serve_sync:
        # requests from a given peer id must never regress.  Bounded by
        # the configured peer set plus strangers (capped).
        self._seen_lock = threading.Lock()
        self._seen: Dict[str, Dict[str, Any]] = {}
        # Last-good-global dual cache (bounded staleness): the newest
        # COMPLETE exchange's duals + remote base loads (every peer
        # contributed every round; tol-convergence not required — see
        # the cache-write comment in _try_global).
        self._cache_lock = threading.Lock()
        self._last_good: Optional[Dict[str, Any]] = None
        self.last_rounds = 0
        self.last_rung: Optional[str] = None
        self._m_rung = metrics.REGISTRY.gauge("klba_federation_rung")
        self._m_staleness = metrics.REGISTRY.gauge(
            "klba_federation_staleness_s"
        )
        self._m_link_state = {
            pid: metrics.REGISTRY.gauge(
                "klba_peer_link_state", {"peer": pid}
            )
            for pid in self._links
        }
        # Async gossip duals (the background convergence plane): a
        # daemon thread re-converges the consumer-axis duals with peers
        # at a jittered cadence, continuously refreshing the last-good
        # cache, so assign() can serve rung "global" from warm duals in
        # ONE local round — no synchronous peer RTT on the serve path.
        # Off by default (interval 0 = today's synchronous exchange).
        # The freshness window bounds how old a gossiped dual set may
        # be and still serve AS "global"; past it the ordinary ladder
        # (synchronous exchange -> last-good -> local-only) takes over.
        self.gossip_interval_s = float(gossip_interval_s)
        self.gossip_freshness_s = (
            float(gossip_freshness_s)
            if gossip_freshness_s is not None
            else min(2.5 * self.gossip_interval_s, self.max_staleness_s)
        )
        self.last_gossip: Optional[Dict[str, Any]] = None
        self._m_gossip = {
            o: metrics.REGISTRY.counter(
                "klba_gossip_rounds_total", {"outcome": o}
            )
            for o in ("ok", "degraded", "idle", "error")
        }
        self._gossip_stop = threading.Event()
        self._gossip_thread: Optional[threading.Thread] = None
        if self.gossip_interval_s > 0 and self._links:
            self._gossip_thread = threading.Thread(
                target=self._gossip_loop,
                name=f"klba-gossip-{self.self_id}", daemon=True,
            )
            self._gossip_thread.start()

    # -- local shard --------------------------------------------------------

    def register_local_shard(self, lags: np.ndarray, C: int) -> int:
        """Install this sidecar's current local lag view (sorted-pid
        order) as the shard peers sync against; bumps the monotone
        local epoch when the vector changed.  Returns the epoch."""
        lags = np.asarray(lags, dtype=np.int64)
        with self._shard_lock:
            prev = self._shard
            changed = (
                prev is None
                or prev["C"] != int(C)
                or prev["lags"].shape != lags.shape
                or not np.array_equal(prev["lags"], lags)
            )
            if changed:
                self.local_epoch += 1
                self._shard = {
                    "lags": lags,
                    "C": int(C),
                    "total": int(lags.sum(dtype=np.int64)),
                    "n": int(lags.shape[0]),
                    "dedup": None,  # (scale, (ws_u, count_u, wsum_u))
                }
            return self.local_epoch

    def _shard_dedup(self, shard: Dict[str, Any], scale: float):
        """Caller holds ``_shard_lock``: the shard's dedup weights
        under ``scale``, cached (one entry — scale is fixed per
        exchange and moves only with the global totals)."""
        from ..ops import fedsolve

        cached = shard["dedup"]
        if cached is not None and abs(cached[0] - scale) < 1e-9:
            return cached[1]
        weights = fedsolve.shard_dedup(
            shard["lags"], np.ones(shard["n"], bool), scale
        )
        shard["dedup"] = (float(scale), weights)
        return weights

    # -- the server half ----------------------------------------------------

    def _served(self, outcome: str) -> None:
        metrics.REGISTRY.counter(
            "klba_peer_sync_served_total", {"outcome": outcome}
        ).inc()

    def _count_stale(self, reason: str) -> None:
        metrics.REGISTRY.counter(
            "klba_peer_stale_duals_total", {"reason": reason}
        ).inc()

    def serve_sync(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Answer one incoming ``peer_sync`` request (the service's
        dispatch calls this).  Never raises for protocol-level
        problems — those are structured rejects the initiator counts;
        malformed requests raise ValueError like any wire input."""
        if not isinstance(params, dict):
            raise ValueError("peer_sync params must be a JSON object")
        sender = params.get("peer_id")
        if not isinstance(sender, str) or not sender:
            raise ValueError("peer_sync params.peer_id must be a string")
        epoch = params.get("epoch")
        if not isinstance(epoch, int) or isinstance(epoch, bool):
            raise ValueError("peer_sync params.epoch must be an integer")
        C = params.get("num_consumers")
        if not isinstance(C, int) or isinstance(C, bool) or C < 1:
            raise ValueError(
                "peer_sync params.num_consumers must be a positive "
                "integer"
            )
        token = self._fence_token()
        if params.get("version") != wire.PROTOCOL_VERSION:
            self._served("version")
            return wire.sync_reject(self.self_id, "version", epoch, C)
        # Monotone epoch + fencing per SENDER: a regressing request is
        # stale state from a rolled-back or fenced-off predecessor —
        # rejected and counted, never served marginals that it would
        # blend into a stale global.
        fence = params.get("fence_token")
        with self._seen_lock:
            rec = self._seen.get(sender)
            if rec is None:
                if len(self._seen) >= 256:
                    # Strangers are bounded (L014) — but ONLY strangers
                    # are evictable: dropping a configured peer's entry
                    # would reset its monotone epoch/fence record and
                    # let a fenced-off predecessor be served again.
                    evictable = next(
                        (k for k in self._seen if k not in self._links),
                        None,
                    )
                    if evictable is None:
                        raise ValueError(
                            "peer ledger full of configured peers"
                        )
                    self._seen.pop(evictable)
                rec = self._seen[sender] = {"epoch": -1, "fence": None}
            if epoch < rec["epoch"]:
                self._count_stale("stale_epoch")
                self._served("stale_epoch")
                return wire.sync_reject(
                    self.self_id, "stale_epoch", self.local_epoch, C
                )
            if fence is not None and rec["fence"] is not None and (
                int(fence) < rec["fence"]
            ):
                self._count_stale("fenced")
                self._served("fenced")
                return wire.sync_reject(
                    self.self_id, "fenced", self.local_epoch, C
                )
            rec["epoch"] = epoch
            if fence is not None:
                rec["fence"] = max(
                    int(fence),
                    rec["fence"] if rec["fence"] is not None else 0,
                )
        with self._shard_lock:
            shard = self._shard
            if shard is None:
                self._served("unavailable")
                return wire.sync_reject(
                    self.self_id, "unavailable", self.local_epoch, C
                )
            if shard["C"] != C:
                self._served("mismatch")
                return wire.sync_reject(
                    self.self_id, "mismatch", self.local_epoch, C
                )
            if params.get("phase") == "hello":
                self._served("ok")
                return wire.sync_response(
                    self.self_id, self.local_epoch,
                    int(params.get("round", 0)), C,
                    total_lag=shard["total"], n_valid=shard["n"],
                    fence_token=token,
                    capacity=self._capacity_for(C),
                )
            duals = params.get("duals") or {}
            a = duals.get("A")
            b = duals.get("B")
            if (
                not isinstance(a, list) or not isinstance(b, list)
                or len(a) != C or len(b) != C
            ):
                raise ValueError(
                    "peer_sync exchange params.duals.A/B must be "
                    "length-C lists"
                )
            scale = float(params.get("scale", 0.0))
            if not scale > 0:
                raise ValueError("peer_sync params.scale must be > 0")
            weights = self._shard_dedup(shard, scale)
            total, n = shard["total"], shard["n"]
            my_epoch = self.local_epoch
        from ..ops import fedsolve

        load, colsum = fedsolve.shard_marginals(
            *weights,
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32),
        )
        self._served("ok")
        return wire.sync_response(
            self.self_id, my_epoch, int(params.get("round", 0)), C,
            total_lag=total, n_valid=n, load=load, colsum=colsum,
            fence_token=token,
        )

    def _capacity_for(self, C: int) -> Optional[list]:
        """This cluster's capacity vector as a wire-ready list, or None
        when unset or shaped for a different roster."""
        cap = self.capacity
        if cap is None or cap.shape != (int(C),):
            return None
        return [float(v) for v in cap]

    # -- the initiator half -------------------------------------------------

    def _sync_once(
        self, link: _PeerLink, params: Dict[str, Any]
    ) -> Dict[str, Any]:
        """One breaker-wrapped peer exchange: transport + protocol +
        staleness validation.  Raises :class:`PeerDropped` on ANY
        reason this peer's contribution cannot be used — the watchdog
        counts consecutive failures toward the peer's breaker, and the
        round loop abandons the global attempt."""
        pid = link.spec.peer_id
        with metrics.span("federation.sync"):
            return self._sync_once_inner(link, pid, params)

    def _sync_once_inner(
        self, link: _PeerLink, pid: str, params: Dict[str, Any]
    ) -> Dict[str, Any]:
        try:
            # Fault point peer.sync: a protocol-level failure inside
            # the exchange (distinct from the transport-level
            # peer.partition) — charged to this peer's breaker.
            faults.fire("peer.sync")
            resp = link.request(params)
        except PeerDropped:
            raise
        except Exception as exc:
            raise PeerDropped(pid, f"transport: {exc}") from exc
        if not isinstance(resp, dict):
            raise PeerDropped(pid, "malformed response")
        rejected = resp.get("rejected")
        if rejected is not None:
            raise PeerDropped(pid, f"rejected: {rejected}")
        epoch = resp.get("epoch")
        if not isinstance(epoch, int):
            raise PeerDropped(pid, "missing epoch")
        stale_reason = None
        try:
            faults.fire("peer.stale_duals")
        except faults.FaultError:
            # The drill's simulated stale peer state: validate as if
            # the response's epoch had regressed.
            stale_reason = "injected"
        if epoch < link.max_epoch_seen:
            stale_reason = "stale_epoch"
        fence = resp.get("fence_token")
        if (
            fence is not None
            and link.max_fence_seen is not None
            and int(fence) < link.max_fence_seen
        ):
            stale_reason = "fenced"
        if stale_reason is not None:
            self._count_stale(stale_reason)
            raise PeerDropped(pid, f"stale duals ({stale_reason})")
        link.max_epoch_seen = max(link.max_epoch_seen, epoch)
        if fence is not None:
            link.max_fence_seen = max(
                int(fence), link.max_fence_seen or 0
            )
        return resp

    def _exchange_round(
        self,
        params_for: Callable[[str], Dict[str, Any]],
        remaining_s: Callable[[], Optional[float]],
    ) -> Optional[Dict[str, Dict[str, Any]]]:
        """One synchronized round against EVERY peer; returns
        ``{peer_id: response}`` or None when any peer failed (partial
        rounds are never used).  Each call runs under that peer's
        breaker with a timeout bounded by both the sync timeout and the
        request's remaining budget — re-read PER PEER, so N slow peers
        cannot stack N x remaining past the request deadline."""
        out: Dict[str, Dict[str, Any]] = {}
        for pid, link in self._links.items():
            timeout = self.sync_timeout_s
            rem = remaining_s()
            if rem is not None:
                timeout = min(timeout, rem)
            if timeout <= 0:
                self._note_peer(pid, "budget")
                return None
            try:
                resp = self._watchdog.call(
                    self._sync_once, link, params_for(pid),
                    key=f"peer:{pid}", timeout_s=timeout,
                )
            except Exception:
                # Transport failure, breaker fail-fast, injected fault,
                # stale/fenced drop — this round cannot complete.  The
                # ladder (not an error) decides what serves.
                LOGGER.warning(
                    "federation round lost peer %r", pid, exc_info=True
                )
                self._note_peer(pid, "error")
                return None
            self._note_peer(pid, "ok")
            out[pid] = resp
        return out

    def _note_peer(self, pid: str, outcome: str) -> None:
        link = self._links[pid]
        link.last_outcome = outcome
        metrics.REGISTRY.counter(
            "klba_peer_sync_total", {"peer": pid, "outcome": outcome}
        ).inc()
        state = self._watchdog.state(f"peer:{pid}")
        self._m_link_state[pid].set(
            {"closed": 0, "half_open": 1, "open": 2}.get(state, 0)
        )

    def assign(
        self,
        lags: np.ndarray,
        C: int,
        remaining_s: Callable[[], Optional[float]],
        refine_iters: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Converge (or degrade to) an assignment for the local shard.

        Returns ``{"rung": ..., "choice": int32[P] | None, "rounds",
        "peers_ok", "staleness_s", "converged"}`` — ``choice`` is None
        exactly at rung ``local_only`` (the caller runs its normal
        single-cluster solve, unchanged).  Never raises for peer
        failures; the ladder is the contract.
        """
        from ..ops import fedsolve

        lags = np.asarray(lags, dtype=np.int64)
        epoch = self.register_local_shard(lags, int(C))
        token = self._fence_token()
        result: Dict[str, Any] = {
            "rung": "local_only", "choice": None, "rounds": 0,
            "peers_ok": 0, "staleness_s": None, "converged": False,
            "warm_cache": False,
        }
        with metrics.span("federation.assign"):
            # Warm-cache fast path: with the gossip daemon keeping the
            # duals converged in the background, a fresh-enough cache
            # serves rung "global" in ONE local rounding call — no
            # synchronous peer RTT on the serve path.  A stale or
            # missing cache falls through to the ordinary ladder.
            attempt = (
                self._round_from_gossip(
                    fedsolve, lags, int(C), refine_iters
                )
                if self.gossip_interval_s > 0 else None
            )
            if attempt is None and self._links:
                attempt = self._try_global(
                    fedsolve, lags, int(C), epoch, token, remaining_s,
                    refine_iters,
                )
            if attempt is not None:
                result.update(attempt)
            else:
                cached = self._round_from_cache(
                    fedsolve, lags, int(C), refine_iters
                )
                if cached is not None:
                    result.update(cached)
        rung = result["rung"]
        self.last_rung = rung
        self._m_rung.set(FEDERATION_RUNGS.index(rung))
        metrics.REGISTRY.counter(
            "klba_federation_assign_total", {"rung": rung}
        ).inc()
        if rung != "global":
            metrics.FLIGHT.record(
                "federation",
                {
                    "event": "degraded",
                    "rung": rung,
                    "staleness_s": result["staleness_s"],
                    "peers_ok": result["peers_ok"],
                },
            )
        return result

    def _try_global(
        self, fedsolve, lags, C, epoch, token, remaining_s, refine_iters
    ) -> Optional[Dict[str, Any]]:
        """The synchronized exchange; None when any round lost a peer
        or the budget ran out (the caller then consults the cache)."""
        conv = self._converge_duals(
            fedsolve, C, epoch, token, remaining_s, phase="exchange"
        )
        if conv is None:
            return None
        self.last_rounds = conv["rounds"]
        choice, _, _ = fedsolve.round_local_shard(
            lags, C, conv["A"], conv["B"], conv["scale"],
            conv["base_load"], refine_iters=refine_iters,
            capacity_frac=conv["cap_frac"],
        )
        self._m_staleness.set(0.0)
        return {
            "rung": "global", "choice": choice,
            "rounds": conv["rounds"], "peers_ok": len(self._links),
            "staleness_s": 0.0, "converged": conv["converged"],
        }

    def _converge_duals(
        self, fedsolve, C, epoch, token, remaining_s,
        phase: str = "exchange",
    ) -> Optional[Dict[str, Any]]:
        """Hello + synchronized dual-exchange rounds against EVERY
        peer, refreshing the last-good cache on completion; None when
        any round lost a peer or the budget ran out.  This ONE body is
        shared verbatim by the synchronous serve path
        (``phase="exchange"``) and the background gossip daemon
        (``phase="gossip"``) — same per-peer breakers, same monotone
        epoch/fence staleness fencing, same complete-round discipline —
        so the only difference between the two planes is who pays the
        RTTs and when."""
        # Handshake: every peer's scalars fix the shared scale/cap.
        hello = self._exchange_round(
            lambda pid: wire.sync_request(
                self.self_id, epoch, 0, C, scale=1.0,
                fence_token=token, phase="hello",
                traceparent=metrics.current_traceparent(),
            ),
            remaining_s,
        )
        if hello is None:
            return None
        with self._shard_lock:
            shard = self._shard
            if shard is None or shard["C"] != C:
                # The gossip daemon races shard registration: no local
                # shard (or a roster flip mid-convergence) simply skips
                # this attempt — nothing to converge against.
                return None
            total = shard["total"]
            n = shard["n"]
        # Weighted shards: every shard's capacity vector (uniform ones
        # when a shard advertises none or sends an unusable one) is
        # NORMALIZED to sum C before summing — the aggregation is then
        # scale-invariant (a cluster reporting [1000, 500] and one
        # reporting [2, 1] express the same preference with the same
        # weight, and an unweighted cluster's uniform vote counts
        # equally).  A peer vector with a NaN/negative entry (the
        # wire audit rejects them at construction, but the response is
        # parsed JSON) is dropped to uniform, counted as stale state.
        # With NO shard weighted, the cap vector degenerates to
        # exactly the uniform n/C marginal.
        def _norm(vec) -> Optional[np.ndarray]:
            if vec is None or not (
                isinstance(vec, (list, np.ndarray)) and len(vec) == C
            ):
                return None
            if not wire.capacity_usable(vec):
                return None
            arr = np.asarray(vec, np.float64)
            return arr * (C / arr.sum())

        own_cap = _norm(self._capacity_for(C))
        cap_vecs = [own_cap if own_cap is not None
                    else np.ones(C, np.float64)]
        any_weighted = own_cap is not None
        for resp in hello.values():
            total += int(resp.get("total_lag", 0))
            n += int(resp.get("n_valid", 0))
            raw_cap = resp.get("capacity")
            peer_cap = _norm(raw_cap)
            if peer_cap is not None:
                cap_vecs.append(peer_cap)
                any_weighted = True
            else:
                if raw_cap is not None:
                    self._count_stale("capacity")
                cap_vecs.append(np.ones(C, np.float64))
        scale = max(float(total), 1.0) / C
        cap_frac: Optional[np.ndarray] = None
        if any_weighted:
            capw = np.sum(cap_vecs, axis=0)
            cap_frac = capw / capw.sum()
            cap = max(float(n), 1.0) * cap_frac
        else:
            cap = max(float(n), 1.0) / C
        with self._shard_lock:
            weights = self._shard_dedup(self._shard, scale)
        A, B = fedsolve.initial_duals(C)
        step_scale, prev_spread = 1.0, float("inf")
        rounds = 0
        converged = False
        remote_load = np.zeros(C, np.float64)
        for r in range(1, self.max_rounds + 1):
            with metrics.span("federation.round"):
                load, colsum = fedsolve.shard_marginals(
                    *weights, A, B
                )
                responses = self._exchange_round(
                    lambda pid: wire.sync_request(
                        self.self_id, epoch, r, C, scale=scale,
                        duals_a=A, duals_b=B, fence_token=token,
                        phase=phase,
                        traceparent=metrics.current_traceparent(),
                    ),
                    remaining_s,
                )
            if responses is None:
                return None
            rounds = r
            load_sum = load.astype(np.float64)
            colsum_sum = colsum.astype(np.float64)
            remote_load = np.zeros(C, np.float64)
            for pid, resp in responses.items():
                marg = resp.get("marginals") or {}
                r_load = np.asarray(
                    marg.get("load", []), dtype=np.float64
                )
                r_col = np.asarray(
                    marg.get("colsum", []), dtype=np.float64
                )
                if r_load.shape != (C,) or r_col.shape != (C,):
                    # A structurally short response cannot be summed;
                    # treat like a lost round.  Keyed by the CONFIGURED
                    # peer id, not the response's self-reported one —
                    # an id the links don't know would raise out of
                    # the never-raises ladder.
                    self._note_peer(pid, "error")
                    return None
                load_sum += r_load
                colsum_sum += r_col
                remote_load += r_load
            A, B, step_scale, spread, delta = fedsolve.dual_step(
                A, B, load_sum, colsum_sum, cap, step_scale,
                prev_spread,
            )
            # Carry the SPREAD (like the leader's loop body): the
            # damping test is "did the load spread grow since last
            # step" — carrying delta (>= spread by construction) would
            # keep `grew` from ever firing once the colsum correction
            # dominates, un-damping exactly the oscillating regime the
            # epsilon-scaled step exists for.
            prev_spread = spread
            if delta <= fedsolve.DUAL_TOL:
                converged = True
                break
        # Cache every COMPLETE exchange (all peers contributed every
        # round) — convergence-by-tol is deliberately NOT required: a
        # budget-bounded exchange that ran its full round budget still
        # yields near-converged duals (bench-measured quality 1.0001 at
        # max_rounds with delta ~3e-5 above tol), and an empty cache
        # would cost the middle rung exactly when partitions follow a
        # slow exchange.
        with self._cache_lock:
            self._last_good = {
                "A": np.asarray(A, np.float32),
                "B": np.asarray(B, np.float32),
                "scale": float(scale),
                "base_load": remote_load.astype(np.float32),
                "C": int(C),
                "at": self._clock(),
                "rounds": rounds,
                # The weighted-count shares (None = uniform) ride the
                # cache so the last-good-global rung rounds with the
                # same capacity apportionment the exchange converged
                # under.
                "cap_frac": cap_frac,
                # Whether the exchange hit DUAL_TOL (vs exhausting the
                # round budget) — the gossip warm-serve path reports it
                # as the served assignment's convergence.
                "converged": converged,
            }
        return {
            "A": A, "B": B, "scale": scale, "base_load": remote_load,
            "rounds": rounds, "converged": converged,
            "cap_frac": cap_frac,
        }

    def _round_from_cache(
        self, fedsolve, lags, C, refine_iters
    ) -> Optional[Dict[str, Any]]:
        """Rung 2: round the local shard with the last-good-global
        duals, inside the bounded-staleness window.  None when the
        cache is empty, too old, or shaped for a different roster —
        the caller then serves local-only."""
        with self._cache_lock:
            cached = dict(self._last_good) if self._last_good else None
        if cached is None or cached["C"] != C:
            return None
        age = self._clock() - cached["at"]
        if age > self.max_staleness_s:
            return None
        choice, _, _ = fedsolve.round_local_shard(
            lags, C, cached["A"], cached["B"], cached["scale"],
            cached["base_load"], refine_iters=refine_iters,
            capacity_frac=cached.get("cap_frac"),
        )
        self._m_staleness.set(age)
        return {
            "rung": "last_good_global", "choice": choice,
            "rounds": cached["rounds"], "peers_ok": 0,
            "staleness_s": age, "converged": False,
        }

    def _round_from_gossip(
        self, fedsolve, lags, C, refine_iters
    ) -> Optional[Dict[str, Any]]:
        """The gossip warm-cache fast path: round the local shard with
        the background-converged duals when the cache is inside the
        gossip FRESHNESS window (much tighter than the last-good rung's
        bounded staleness — these duals must be current enough to
        *count as* rung "global").  None falls through to the ordinary
        ladder."""
        with self._cache_lock:
            cached = dict(self._last_good) if self._last_good else None
        if cached is None or cached["C"] != C:
            return None
        age = self._clock() - cached["at"]
        if age > self.gossip_freshness_s:
            return None
        choice, _, _ = fedsolve.round_local_shard(
            lags, C, cached["A"], cached["B"], cached["scale"],
            cached["base_load"], refine_iters=refine_iters,
            capacity_frac=cached.get("cap_frac"),
        )
        self._m_staleness.set(age)
        return {
            "rung": "global", "choice": choice,
            "rounds": cached["rounds"],
            "peers_ok": len(self._links), "staleness_s": age,
            "converged": bool(cached.get("converged", False)),
            "warm_cache": True,
        }

    # -- the gossip daemon --------------------------------------------------

    def gossip_now(self) -> str:
        """One background convergence attempt (the daemon's body, also
        callable directly by tests and the scenario runner for
        deterministic cadence).  Returns the outcome counted into
        ``klba_gossip_rounds_total``: ``ok`` (cache refreshed),
        ``degraded`` (a peer was lost — the cache keeps its previous
        entry and ages), or ``idle`` (no shard registered / no peers
        yet — nothing to converge against)."""
        from ..ops import fedsolve

        with self._shard_lock:
            shard = self._shard
            C = int(shard["C"]) if shard is not None else None
        if C is None or not self._links:
            outcome = "idle"
        else:
            with metrics.span("federation.gossip"):
                conv = self._converge_duals(
                    fedsolve, C, self.local_epoch, self._fence_token(),
                    lambda: None, phase="gossip",
                )
            outcome = "ok" if conv is not None else "degraded"
        self._m_gossip[outcome].inc()
        self.last_gossip = {"outcome": outcome, "at": self._clock()}
        return outcome

    def _gossip_loop(self) -> None:
        # Jittered cadence (0.75x-1.25x the configured interval, from a
        # per-sidecar deterministic stream): peers started together must
        # not phase-lock their gossip rounds into synchronized RTT
        # bursts against each other.
        import random

        rng = random.Random(f"gossip:{self.self_id}")
        while not self._gossip_stop.is_set():
            wait_s = self.gossip_interval_s * (0.75 + 0.5 * rng.random())
            if self._gossip_stop.wait(wait_s):
                return
            try:
                self.gossip_now()
            except Exception:
                # The daemon must survive anything a round can throw
                # (the serve path never depends on it succeeding).
                LOGGER.warning("gossip round failed", exc_info=True)
                self._m_gossip["error"].inc()
                self.last_gossip = {
                    "outcome": "error", "at": self._clock()
                }

    # -- operator surface ---------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The wire ``federation`` method / ``stats.federation``
        section."""
        with self._cache_lock:
            cached = self._last_good
            cache_info = (
                {
                    "age_s": self._clock() - cached["at"],
                    "rounds": cached["rounds"],
                    "num_consumers": cached["C"],
                }
                if cached else None
            )
        peers = {}
        for pid, link in self._links.items():
            peers[pid] = {
                "address": f"{link.spec.host}:{link.spec.port}",
                "breaker": self._watchdog.state(f"peer:{pid}"),
                "last_outcome": link.last_outcome,
                "epoch_seen": link.max_epoch_seen,
                "fence_seen": link.max_fence_seen,
            }
        return {
            "self_id": self.self_id,
            "epoch": self.local_epoch,
            "rung": self.last_rung,
            "last_rounds": self.last_rounds,
            "max_rounds": self.max_rounds,
            "sync_timeout_s": self.sync_timeout_s,
            "max_staleness_s": self.max_staleness_s,
            "last_good": cache_info,
            "gossip": {
                "interval_s": self.gossip_interval_s,
                "freshness_s": self.gossip_freshness_s,
                "thread_alive": (
                    self._gossip_thread is not None
                    and self._gossip_thread.is_alive()
                ),
                "last": (
                    {
                        "outcome": self.last_gossip["outcome"],
                        "age_s": (
                            self._clock() - self.last_gossip["at"]
                        ),
                    }
                    if self.last_gossip is not None else None
                ),
            },
            "peers": peers,
        }

    # -- lifecycle snapshot (utils/snapshot) --------------------------------

    def export_state(self) -> Dict[str, Any]:
        """Host-durable federation state for the lifecycle snapshot:
        the monotone local epoch (it must survive restarts or peers
        would reject the replacement as stale), the per-peer ledger,
        and the last-good-global duals (age stored relative to the
        write so it rebases on load).  The snapshot save itself is
        fenced by the round-14 writer tokens, so a fenced-off
        predecessor cannot clobber the successor's federation state."""
        with self._cache_lock:
            cached = self._last_good
            cache = None
            if cached is not None:
                cap_frac = cached.get("cap_frac")
                cache = {
                    "A": [float(v) for v in cached["A"]],
                    "B": [float(v) for v in cached["B"]],
                    "scale": cached["scale"],
                    "base_load": [float(v) for v in cached["base_load"]],
                    "C": cached["C"],
                    "age_s": self._clock() - cached["at"],
                    "rounds": cached["rounds"],
                    "cap_frac": (
                        [float(v) for v in cap_frac]
                        if cap_frac is not None else None
                    ),
                }
        return {
            "epoch": self.local_epoch,
            "peer_epochs": {
                pid: link.max_epoch_seen
                for pid, link in self._links.items()
            },
            "peer_fences": {
                pid: link.max_fence_seen
                for pid, link in self._links.items()
            },
            "last_good": cache,
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Adopt exported federation state after a restart; malformed
        input is discarded whole (fail-open, like every recovery
        section)."""
        try:
            epoch = int(state.get("epoch", 0))
            cache = state.get("last_good")
            peer_epochs = dict(state.get("peer_epochs") or {})
            peer_fences = dict(state.get("peer_fences") or {})
        except (TypeError, ValueError, AttributeError):
            LOGGER.warning(
                "discarding malformed federation snapshot", exc_info=True
            )
            return
        self.local_epoch = max(self.local_epoch, epoch)
        for pid, link in self._links.items():
            try:
                if pid in peer_epochs:
                    link.max_epoch_seen = max(
                        link.max_epoch_seen, int(peer_epochs[pid])
                    )
                fence = peer_fences.get(pid)
                if fence is not None:
                    link.max_fence_seen = max(
                        int(fence), link.max_fence_seen or 0
                    )
            except (TypeError, ValueError):
                LOGGER.warning(
                    "discarding malformed peer ledger for %r", pid,
                    exc_info=True,
                )
        if cache is not None:
            try:
                C = int(cache["C"])
                restored = {
                    "A": np.asarray(cache["A"], np.float32),
                    "B": np.asarray(cache["B"], np.float32),
                    "scale": float(cache["scale"]),
                    "base_load": np.asarray(
                        cache["base_load"], np.float32
                    ),
                    "C": C,
                    "at": self._clock() - max(
                        float(cache.get("age_s", 0.0)), 0.0
                    ),
                    "rounds": int(cache.get("rounds", 0)),
                    "cap_frac": (
                        np.asarray(cache["cap_frac"], np.float64)
                        if cache.get("cap_frac") is not None else None
                    ),
                }
                cf = restored["cap_frac"]
                if cf is not None and cf.shape != (C,):
                    restored["cap_frac"] = None
                if (
                    restored["A"].shape == (C,)
                    and restored["B"].shape == (C,)
                    and restored["base_load"].shape == (C,)
                ):
                    with self._cache_lock:
                        self._last_good = restored
            except (TypeError, ValueError, KeyError):
                LOGGER.warning(
                    "discarding malformed last-good dual cache",
                    exc_info=True,
                )

    def close(self) -> None:
        self._gossip_stop.set()
        thread = self._gossip_thread
        if thread is not None and thread.is_alive():
            # Bounded join: a gossip round mid-RTT finishes within the
            # per-peer sync timeout; don't hang shutdown past it.
            thread.join(timeout=self.sync_timeout_s + 1.0)
        for link in self._links.values():
            link.close()
