"""Federated multi-cluster assignment (DEPLOYMENT.md "Federated
assignment").

Several sidecars, each co-located with its own Kafka cluster and
holding only its LOCAL lag shard, converge one global assignment by
exchanging consumer-axis duals/marginals — raw per-partition lags never
leave a cluster (the Federated Sinkhorn split, arXiv:2502.07021;
device math in :mod:`..ops.fedsolve`).  This package owns the protocol
and the robustness around it:

* :mod:`.wire` — THE audited serializer for every peer-bound payload
  (lint L019 confines construction here): whitelisted keys, C-bounded
  vectors, and the raw-lag byte audit the bench gate runs on-wire.
* :mod:`.peers` — the coordination layer: per-peer links with circuit
  breakers (utils/watchdog), synchronized dual-exchange rounds inside
  the request's deadline budget, bounded-staleness dual caching with
  monotone epoch + fencing-token rejection, and the degradation ladder
  ``global`` -> ``last_good_global`` -> ``local_only`` that fails open
  to exactly the single-cluster behavior when every peer is gone.
"""

from .peers import (
    FEDERATION_RUNGS,
    FederationCoordinator,
    PeerSpec,
    parse_peer_specs,
)
from .wire import PEER_SYNC_METHOD, assert_lag_free

__all__ = [
    "FEDERATION_RUNGS",
    "FederationCoordinator",
    "PeerSpec",
    "parse_peer_specs",
    "PEER_SYNC_METHOD",
    "assert_lag_free",
]
