"""THE audited serializer for peer-bound federation payloads.

Privacy is the federation's load-bearing contract: a ``peer_sync``
exchange may carry consumer-axis (C-dimensional) aggregates and
scalars, NEVER the partition-axis lag vector — raw lags do not leave
the cluster that observed them.  That guarantee is only auditable if
every peer-bound payload is constructed in ONE place, so lint rule
L019 confines construction to this module: requests are built by
:func:`sync_request`, responses by :func:`sync_response` /
:func:`sync_reject`, and both run :func:`_check_payload` — a
WHITELIST walk (unknown keys are a bug, not a pass-through) that also
bounds every numeric list to the declared consumer count, so a
P-length lag vector cannot ride out even under an allowed key.

:func:`assert_lag_free` is the on-wire audit the bench gate and the
chaos suite run against captured payload bytes: no window of the raw
lag vector may appear serialized anywhere in the payload.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

#: The peer-coordination wire method (service dispatch + L019 anchor).
PEER_SYNC_METHOD = "peer_sync"

#: Protocol version: a peer answering a different version is dropped
#: (counted), never half-parsed.
PROTOCOL_VERSION = 1

#: Whitelisted payload keys per direction.  ``duals``/``marginals`` are
#: dicts of C-bounded f32 lists; everything else is a scalar/string.
_REQUEST_KEYS = frozenset(
    {
        "version", "peer_id", "epoch", "fence_token", "round",
        "num_consumers", "scale", "phase", "duals", "traceparent",
    }
)
_RESPONSE_KEYS = frozenset(
    {
        "version", "peer_id", "epoch", "fence_token", "round",
        "num_consumers", "marginals", "total_lag", "n_valid",
        "rejected", "capacity",
    }
)
_DUALS_KEYS = frozenset({"A", "B"})
_MARGINAL_KEYS = frozenset({"load", "colsum"})

#: Reject reasons a peer may answer instead of marginals.
REJECT_REASONS = (
    "stale_epoch", "fenced", "unavailable", "mismatch", "version",
)


class PayloadViolation(ValueError):
    """A peer-bound payload failed the whitelist/shape audit — raised at
    CONSTRUCTION time, so a privacy-violating payload can never reach a
    socket."""


def _check_vector(key: str, value: Any, C: int) -> List[float]:
    if not isinstance(value, (list, np.ndarray)):
        raise PayloadViolation(f"{key} must be a numeric list")
    out = [float(v) for v in np.asarray(value, dtype=np.float64)]
    if len(out) != C:
        # THE shape audit: every vector on the peer wire lives on the
        # consumer axis.  A partition-axis vector (P >> C in every real
        # deployment, and never equal to the declared C here) cannot be
        # smuggled under an allowed key.
        raise PayloadViolation(
            f"{key} has length {len(out)}, expected the declared "
            f"num_consumers {C} — partition-axis data may not ride the "
            "peer wire"
        )
    return out


def _check_payload(
    payload: Dict[str, Any], allowed: frozenset, C: int
) -> None:
    unknown = set(payload) - allowed
    if unknown:
        raise PayloadViolation(
            f"peer payload carries non-whitelisted keys {sorted(unknown)}"
        )
    tp = payload.get("traceparent")
    if tp is not None:
        # Trace context rides the peer wire as ONE fixed-length scalar
        # string (W3C traceparent) — length-checked and re-parsed here
        # so the tracing plane cannot become a covert channel for
        # anything wider than two ids and a flag byte.
        from ..utils import trace as trace_mod

        if (
            not isinstance(tp, str)
            or len(tp) != trace_mod.TRACEPARENT_LEN
            or trace_mod.parse_traceparent(tp) is None
        ):
            raise PayloadViolation(
                "traceparent must be a single W3C traceparent scalar "
                f"({trace_mod.TRACEPARENT_LEN} chars)"
            )
    duals = payload.get("duals")
    if duals is not None:
        if set(duals) - _DUALS_KEYS:
            raise PayloadViolation("duals may carry only A/B")
        for key in _DUALS_KEYS:
            payload["duals"][key] = _check_vector(f"duals.{key}",
                                                  duals[key], C)
    marginals = payload.get("marginals")
    if marginals is not None:
        if set(marginals) - _MARGINAL_KEYS:
            raise PayloadViolation("marginals may carry only load/colsum")
        for key in _MARGINAL_KEYS:
            payload["marginals"][key] = _check_vector(
                f"marginals.{key}", marginals[key], C
            )
    capacity = payload.get("capacity")
    if capacity is not None:
        # The weighted-shard capacity vector rides the SAME consumer-
        # axis shape audit as the marginals: C-bounded, so a
        # partition-axis vector cannot smuggle out under this key —
        # and every entry must be a finite positive weight (a NaN or
        # negative capacity would poison the summed global count
        # marginal; the initiator re-checks with the same rule).
        vec = _check_vector("capacity", capacity, C)
        if not capacity_usable(vec):
            raise PayloadViolation(
                "capacity entries must be finite and > 0"
            )
        payload["capacity"] = vec


def capacity_usable(vec) -> bool:
    """True when ``vec`` is a usable capacity weight vector: every
    entry finite and strictly positive.  Shared by the construction
    audit above and the INITIATOR's consumption of a peer's hello
    response (a hostile/buggy peer's NaN or negative entry must never
    reach the summed count marginal)."""
    arr = np.asarray(vec, dtype=np.float64)
    return bool(np.all(np.isfinite(arr)) and np.all(arr > 0))


def sync_request(
    peer_id: str,
    epoch: int,
    round_index: int,
    num_consumers: int,
    scale: float,
    duals_a: Optional[Any] = None,
    duals_b: Optional[Any] = None,
    fence_token: Optional[int] = None,
    phase: str = "exchange",
    traceparent: Optional[str] = None,
) -> Dict[str, Any]:
    """Build (and audit) one ``peer_sync`` request's params.

    ``phase`` is ``"hello"`` for the handshake round (no duals yet —
    the response's ``total_lag``/``n_valid`` scalars fix the shared
    scale), ``"exchange"`` for a marginal round under the carried
    duals, or ``"gossip"`` for the SAME marginal round issued by the
    background dual-gossip daemon (identical payload shape and audit —
    consumer-axis duals only, lag-free — the distinct phase tag exists
    so captures and peers can tell the planes apart).  ``traceparent``
    (optional) carries the initiator's W3C trace context so both
    sidecars' segments of a federated assign reconstruct as one trace;
    it is audited as a fixed-length scalar by :func:`_check_payload`."""
    if phase not in ("hello", "exchange", "gossip"):
        raise PayloadViolation(f"unknown phase {phase!r}")
    params: Dict[str, Any] = {
        "version": PROTOCOL_VERSION,
        "peer_id": str(peer_id),
        "epoch": int(epoch),
        "round": int(round_index),
        "num_consumers": int(num_consumers),
        "scale": float(scale),
        "phase": phase,
    }
    if fence_token is not None:
        params["fence_token"] = int(fence_token)
    if duals_a is not None:
        params["duals"] = {"A": duals_a, "B": duals_b}
    if traceparent is not None:
        params["traceparent"] = str(traceparent)
    _check_payload(params, _REQUEST_KEYS, int(num_consumers))
    return params


def sync_response(
    peer_id: str,
    epoch: int,
    round_index: int,
    num_consumers: int,
    total_lag: int,
    n_valid: int,
    load: Optional[Any] = None,
    colsum: Optional[Any] = None,
    fence_token: Optional[int] = None,
    capacity: Optional[Any] = None,
) -> Dict[str, Any]:
    """Build (and audit) one ``peer_sync`` response body: the peer's
    marginal contribution (exchange phase) or just its handshake
    scalars (hello phase — ``load``/``colsum`` None).  ``capacity``
    (hello phase, optional) is this shard's per-consumer capacity
    weight vector — the weighted-shard count marginal's raw material
    (ROADMAP federated (c)); consumer-axis bounded like every vector
    on this wire."""
    body: Dict[str, Any] = {
        "version": PROTOCOL_VERSION,
        "peer_id": str(peer_id),
        "epoch": int(epoch),
        "round": int(round_index),
        "num_consumers": int(num_consumers),
        "total_lag": int(total_lag),
        "n_valid": int(n_valid),
    }
    if fence_token is not None:
        body["fence_token"] = int(fence_token)
    if load is not None:
        body["marginals"] = {"load": load, "colsum": colsum}
    if capacity is not None:
        body["capacity"] = capacity
    _check_payload(body, _RESPONSE_KEYS, int(num_consumers))
    return body


def sync_reject(
    peer_id: str, reason: str, epoch: int, num_consumers: int
) -> Dict[str, Any]:
    """A structured peer-side rejection (stale epoch, fenced token,
    no registered shard, roster mismatch): the initiator DROPS this
    peer's contribution for the round and counts it — rejected state
    is never averaged in."""
    if reason not in REJECT_REASONS:
        raise PayloadViolation(f"unknown reject reason {reason!r}")
    body = {
        "version": PROTOCOL_VERSION,
        "peer_id": str(peer_id),
        "epoch": int(epoch),
        "num_consumers": int(num_consumers),
        "rejected": reason,
    }
    _check_payload(body, _RESPONSE_KEYS, int(num_consumers))
    return body


def encode(payload: Dict[str, Any]) -> bytes:
    """Serialize one audited payload (the capture point the bench's
    on-wire audit reads)."""
    return json.dumps(payload).encode()


def assert_lag_free(payload: bytes, lags, window: int = 3) -> None:
    """The on-wire audit: no ``window`` consecutive raw lag values may
    appear serialized (as a JSON fragment, any of the idiomatic
    spellings) anywhere in ``payload``.  Raises AssertionError with the
    offending fragment; used by the bench gate and the chaos suite
    against captured ``peer_sync`` bytes."""
    text = payload.decode(errors="replace")
    rows = [int(v) for v in np.asarray(lags).reshape(-1)]
    for i in range(max(0, len(rows) - window + 1)):
        chunk = rows[i: i + window]
        for sep in (", ", ","):
            frag = sep.join(str(v) for v in chunk)
            if frag in text:
                raise AssertionError(
                    f"peer payload leaks raw lag window {chunk} "
                    f"(fragment {frag!r})"
                )
