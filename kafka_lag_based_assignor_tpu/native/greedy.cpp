// Native host-side greedy LPT assignment core.
//
// Exact reference semantics (LagBasedPartitionAssignor.java:204-308) as a
// heap-based O(P log P + P log C) C++ routine — the framework's
// accelerator-independent fast path: used when no TPU is reachable (the
// host-fallback row of SURVEY §5) and as a fair single-thread baseline for
// benchmarks.  The JVM original does an O(C) linear scan per partition
// (Collections.min, :240-263); a binary heap keyed on the same comparator
// (count, total lag, member rank) gives identical output in O(log C) per
// step because the selection key of every non-popped consumer is unchanged
// by an assignment (only the popped consumer's key changes).
//
// ABI: plain C, int64/int32 columns, caller-allocated output. Consumers are
// dense ranks 0..C-1 in lexicographic member-id order (the package-wide
// convention), so rank comparison == member-id comparison.

#include <algorithm>
#include <cstdint>
#include <queue>
#include <vector>

namespace {

struct ConsumerKey {
  int64_t count;
  int64_t total;
  int32_t rank;
};

struct KeyGreater {
  bool operator()(const ConsumerKey& a, const ConsumerKey& b) const {
    if (a.count != b.count) return a.count > b.count;
    if (a.total != b.total) return a.total > b.total;
    return a.rank > b.rank;
  }
};

}  // namespace

extern "C" {

// Assign P partitions to C consumers.  lags/partition_ids are parallel
// arrays of length P; out_choice receives the consumer rank per input row.
// Returns 0 on success, nonzero on invalid arguments.
int klba_assign_greedy(const int64_t* lags, const int32_t* partition_ids,
                       int64_t num_partitions, int32_t num_consumers,
                       int32_t* out_choice) {
  if (num_partitions < 0 || num_consumers <= 0 || (!lags && num_partitions) ||
      (!partition_ids && num_partitions) || (!out_choice && num_partitions)) {
    return 1;
  }

  // Processing order: lag descending, partition id ascending
  // (reference :228-235).
  std::vector<int64_t> order(static_cast<size_t>(num_partitions));
  for (int64_t i = 0; i < num_partitions; ++i) order[static_cast<size_t>(i)] = i;
  std::sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    if (lags[a] != lags[b]) return lags[a] > lags[b];
    return partition_ids[a] < partition_ids[b];
  });

  std::priority_queue<ConsumerKey, std::vector<ConsumerKey>, KeyGreater> heap;
  for (int32_t c = 0; c < num_consumers; ++c) heap.push({0, 0, c});

  for (int64_t i = 0; i < num_partitions; ++i) {
    const int64_t row = order[static_cast<size_t>(i)];
    ConsumerKey best = heap.top();
    heap.pop();
    out_choice[row] = best.rank;
    best.count += 1;
    best.total += lags[row];
    heap.push(best);
  }
  return 0;
}

}  // extern "C"
