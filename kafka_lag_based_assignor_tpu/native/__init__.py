"""ctypes loader for the native greedy core (builds on demand with g++).

The shared library is compiled once per machine into this directory; if the
toolchain is unavailable the caller falls back to the pure-Python oracle —
an import of this module never hard-fails a rebalance.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..types import AssignmentMap, TopicPartitionLag

LOGGER = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "greedy.cpp")
_LIB = os.path.join(_DIR, "libklba_native.so")
_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> None:
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _LIB, _SRC],
        check=True,
        capture_output=True,
    )


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    with _LOCK:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(
                _LIB
            ) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_LIB)
            lib.klba_assign_greedy.restype = ctypes.c_int
            lib.klba_assign_greedy.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            _lib = lib
        except Exception:
            LOGGER.warning("native greedy core unavailable", exc_info=True)
            _load_failed = True
        return _lib


def available() -> bool:
    return load() is not None


def assign_topic_native(
    lags: np.ndarray, partition_ids: np.ndarray, num_consumers: int
) -> np.ndarray:
    """Run the native core on one topic's columns; returns choice int32[P]."""
    lib = load()
    if lib is None:
        raise RuntimeError("native greedy core unavailable")
    lags = np.ascontiguousarray(lags, dtype=np.int64)
    pids = np.ascontiguousarray(partition_ids, dtype=np.int32)
    out = np.empty(lags.shape[0], dtype=np.int32)
    rc = lib.klba_assign_greedy(
        lags.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(lags.shape[0]),
        ctypes.c_int32(num_consumers),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise ValueError(f"klba_assign_greedy failed with code {rc}")
    return out


def assign_native(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
) -> AssignmentMap:
    """Map-level native solve — same surface and exact same output as the
    Python oracle and the device dispatch."""
    from ..ops.dispatch import assign_per_topic

    return assign_per_topic(
        partition_lag_per_topic, subscriptions, assign_topic_native
    )
