"""Lag acquisition (the I/O layer, L2) and the pure lag formula.

Reference semantics reproduced exactly:

* ``compute_partition_lag`` — LagBasedPartitionAssignor.java:376-404:
  committed offset wins; otherwise ``auto.offset.reset=latest`` means lag 0
  and any other mode means the full backlog (end - begin); the result is
  clamped to >= 0 to guard failed end-offset reads.
* ``read_topic_partition_lags`` — LagBasedPartitionAssignor.java:317-365:
  per topic, consult cluster metadata; if a topic has no metadata, warn and
  skip it; otherwise batch-read beginning/end/committed offsets from the
  broker client and compute per-partition lag.

The broker client is abstracted behind ``MetadataConsumer`` so the I/O shell
is testable with a fake — the reference left this layer untested (SURVEY §4).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Protocol,
    Sequence,
    Set,
)

from .types import (
    Cluster,
    LagMap,
    OffsetAndMetadata,
    TopicPartition,
    TopicPartitionLag,
)
from .utils import faults, metrics

LOGGER = logging.getLogger(__name__)


@dataclass(frozen=True)
class LagRetryPolicy:
    """Opt-in bounded retry for the three lag batch RPCs.

    The DEFAULT (no policy) preserves reference abort semantics exactly:
    a broker exception propagates and fails the rebalance (SURVEY
    §2.4.9).  With a policy, each RPC is attempted up to ``attempts``
    times with deterministic exponential backoff
    (``backoff_s * multiplier**i`` — no jitter, so a drill replays the
    same schedule) before the final exception propagates.  ``sleep`` is
    injectable so tests assert the backoff sequence without real sleeps.
    """

    attempts: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts={self.attempts} must be >= 1")


def _call_with_retry(
    fn: Callable[[], Mapping], what: str, retry: Optional[LagRetryPolicy]
):
    """Run one batch RPC under the (optional) retry policy."""
    if retry is None or retry.attempts <= 1:
        return fn()
    for attempt in range(retry.attempts):
        try:
            return fn()
        except Exception:
            if attempt == retry.attempts - 1:
                raise
            metrics.REGISTRY.counter(
                "klba_lag_retries_total", {"rpc": what}
            ).inc()
            delay = retry.backoff_s * retry.multiplier**attempt
            LOGGER.warning(
                "lag RPC %s failed (attempt %d/%d); retrying in %.3fs",
                what, attempt + 1, retry.attempts, delay, exc_info=True,
            )
            retry.sleep(delay)
    raise AssertionError("unreachable")  # the loop returns or raises


def compute_partition_lag(
    partition_metadata: Optional[OffsetAndMetadata],
    begin_offset: int,
    end_offset: int,
    auto_offset_reset_mode: str,
) -> int:
    """Pure lag formula; exact parity with reference :376-404.

    lag = max(end_offset - next_offset, 0) where next_offset is the committed
    offset if present, else end_offset when auto.offset.reset=latest
    (case-insensitive), else begin_offset (earliest / none / anything else).
    """
    if partition_metadata is not None:
        next_offset = partition_metadata.offset
    elif auto_offset_reset_mode.lower() == "latest":
        next_offset = end_offset
    else:
        # assume earliest (reference :393-396: any non-"latest" mode,
        # including "none", takes the earliest branch)
        next_offset = begin_offset
    return max(end_offset - next_offset, 0)


class MetadataConsumer(Protocol):
    """The slice of KafkaConsumer the lag reader uses (reference :339-342).

    Three blocking batch RPCs per topic: ListOffsets (begin), ListOffsets
    (end), OffsetFetch (committed).  Exceptions are deliberately NOT caught —
    a broker failure must abort the rebalance, matching reference semantics
    (SURVEY §2.4.9).
    """

    def beginning_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]: ...

    def end_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]: ...

    def committed(
        self, partitions: Set[TopicPartition]
    ) -> Mapping[TopicPartition, Optional[OffsetAndMetadata]]: ...


def read_topic_partition_lags(
    metadata_consumer: MetadataConsumer,
    cluster: Cluster,
    all_subscribed_topics: Iterable[str],
    auto_offset_reset_mode: str = "latest",
    retry: Optional[LagRetryPolicy] = None,
) -> LagMap:
    """Fetch current consumer-group lag for every partition of every topic.

    Exact behavioral parity with reference :317-365:
    * topics with null/empty cluster metadata are warned about and excluded
      from the result map entirely (:358-360);
    * missing begin/end offsets for a partition default to 0 (:350-351);
    * ``committed`` may omit partitions or map them to None — both mean "no
      committed offset" (:349).

    ``retry`` (default None = reference abort semantics) bounds transient
    broker failures per RPC — see :class:`LagRetryPolicy`.  The fault
    points ``lag.begin`` / ``lag.end`` / ``lag.committed`` sit INSIDE the
    retried callables so injection drills exercise the retry path.
    """
    topic_partition_lags: Dict[str, List[TopicPartitionLag]] = {}
    with metrics.span("lag.read"):
        _read_all(
            topic_partition_lags, metadata_consumer, cluster,
            all_subscribed_topics, auto_offset_reset_mode, retry,
        )
    return topic_partition_lags


def _read_all(
    topic_partition_lags, metadata_consumer, cluster,
    all_subscribed_topics, auto_offset_reset_mode, retry,
):
    for topic in all_subscribed_topics:
        partition_info = cluster.partitions_for_topic(topic)
        if not partition_info:
            LOGGER.warning(
                "Skipping assignment for topic %s since no metadata is available",
                topic,
            )
            continue

        topic_partitions = [
            TopicPartition(p.topic, p.partition) for p in partition_info
        ]
        rows: List[TopicPartitionLag] = []

        # The three batch RPCs — the only network boundary in the plugin.
        def _begin():
            faults.fire("lag.begin")
            return metadata_consumer.beginning_offsets(topic_partitions)

        def _end():
            faults.fire("lag.end")
            return metadata_consumer.end_offsets(topic_partitions)

        def _committed():
            faults.fire("lag.committed")
            return metadata_consumer.committed(set(topic_partitions))

        begin_offsets = _call_with_retry(_begin, "beginning_offsets", retry)
        end_offsets = _call_with_retry(_end, "end_offsets", retry)
        committed = _call_with_retry(_committed, "committed", retry)

        for tp in topic_partitions:
            lag = compute_partition_lag(
                committed.get(tp),
                begin_offsets.get(tp, 0),
                end_offsets.get(tp, 0),
                auto_offset_reset_mode,
            )
            rows.append(TopicPartitionLag(tp.topic, tp.partition, lag))
        topic_partition_lags[topic] = rows
