"""Lag acquisition (the I/O layer, L2) and the pure lag formula.

Reference semantics reproduced exactly:

* ``compute_partition_lag`` — LagBasedPartitionAssignor.java:376-404:
  committed offset wins; otherwise ``auto.offset.reset=latest`` means lag 0
  and any other mode means the full backlog (end - begin); the result is
  clamped to >= 0 to guard failed end-offset reads.
* ``read_topic_partition_lags`` — LagBasedPartitionAssignor.java:317-365:
  per topic, consult cluster metadata; if a topic has no metadata, warn and
  skip it; otherwise batch-read beginning/end/committed offsets from the
  broker client and compute per-partition lag.

The broker client is abstracted behind ``MetadataConsumer`` so the I/O shell
is testable with a fake — the reference left this layer untested (SURVEY §4).

:class:`LagDeltaTracker` adds the DELTA-EPOCH differ (service.py "Delta
epochs"): consecutive lag reads become sparse ``lag_delta`` wire params
whenever little changed, with automatic dense re-seeding on resync — so
existing read-everything clients get O(changed) uploads for free.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Protocol,
    Sequence,
    Set,
)

from .types import (
    Cluster,
    LagMap,
    OffsetAndMetadata,
    TopicPartition,
    TopicPartitionLag,
)
from .utils import faults, metrics

LOGGER = logging.getLogger(__name__)


@dataclass(frozen=True)
class LagRetryPolicy:
    """Opt-in bounded retry for the three lag batch RPCs.

    The DEFAULT (no policy) preserves reference abort semantics exactly:
    a broker exception propagates and fails the rebalance (SURVEY
    §2.4.9).  With a policy, each RPC is attempted up to ``attempts``
    times with deterministic exponential backoff
    (``backoff_s * multiplier**i`` — no jitter, so a drill replays the
    same schedule) before the final exception propagates.  ``sleep`` is
    injectable so tests assert the backoff sequence without real sleeps.
    """

    attempts: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts={self.attempts} must be >= 1")


def _call_with_retry(
    fn: Callable[[], Mapping], what: str, retry: Optional[LagRetryPolicy]
):
    """Run one batch RPC under the (optional) retry policy."""
    if retry is None or retry.attempts <= 1:
        return fn()
    for attempt in range(retry.attempts):
        try:
            return fn()
        except Exception:
            if attempt == retry.attempts - 1:
                raise
            metrics.REGISTRY.counter(
                "klba_lag_retries_total", {"rpc": what}
            ).inc()
            delay = retry.backoff_s * retry.multiplier**attempt
            LOGGER.warning(
                "lag RPC %s failed (attempt %d/%d); retrying in %.3fs",
                what, attempt + 1, retry.attempts, delay, exc_info=True,
            )
            retry.sleep(delay)
    raise AssertionError("unreachable")  # the loop returns or raises


class LagDeltaTracker:
    """Host-side differ for DELTA EPOCHS (service.py "Delta epochs"):
    turns consecutive per-stream lag reads into the smallest valid
    ``stream_assign`` params — a sparse ``lag_delta`` when little
    changed, full ``lags`` rows whenever a dense base must be
    (re)established — so the JVM shim (or any client that simply
    re-reads lags each epoch) benefits from sparse uploads with no
    protocol change of its own.

    Usage, once per stream per epoch::

        params = tracker.params_for(rows)      # {"lags": ...} or
                                               # {"lag_delta": ...}
        result = client.stream_assign(..., **params)
        tracker.note_result(result)            # adopt lag_epoch/resync

    The tracker sends dense until the server confirms a base
    (``stream.lag_epoch``), diffs against the last CONFIRMED rows after
    that, and falls back to dense whenever the pid set changed, more
    than ``max_fraction`` of the partitions moved (the server would
    upload dense anyway), the server answered ``resync: true``, or the
    previous request failed outright.  Fault point ``delta.diff`` fires
    inside the differ — an injected failure degrades to dense, never to
    a lost epoch."""

    def __init__(self, max_fraction: float = 0.125):
        if not 0.0 < float(max_fraction) <= 1.0:
            raise ValueError(
                f"max_fraction={max_fraction} must be in (0, 1]"
            )
        self.max_fraction = float(max_fraction)
        self._base: Optional[Dict[int, int]] = None  # pid -> lag
        self._base_epoch: Optional[int] = None
        self._pending: Optional[Dict[int, int]] = None  # awaiting confirm

    def params_for(self, rows: Sequence) -> Dict[str, Any]:
        """``rows`` is the epoch's full ``[[pid, lag], ...]`` read (any
        order).  Returns the params fragment to merge into the
        ``stream_assign`` request."""
        current = {int(p): int(lag) for p, lag in rows}
        self._pending = current
        base, epoch = self._base, self._base_epoch
        if base is None or epoch is None or set(base) != set(current):
            return {"lags": [[p, v] for p, v in current.items()]}
        try:
            faults.fire("delta.diff")
            changed = [
                (p, v) for p, v in current.items() if base[p] != v
            ]
        except Exception:  # noqa: BLE001 — dense is the safe fallback
            LOGGER.warning(
                "lag delta diff failed; sending dense", exc_info=True
            )
            return {"lags": [[p, v] for p, v in current.items()]}
        if len(changed) > self.max_fraction * max(len(current), 1):
            return {"lags": [[p, v] for p, v in current.items()]}
        return {
            "lag_delta": {
                "indices": [p for p, _ in changed],
                "values": [v for _, v in changed],
                "base_epoch": epoch,
            }
        }

    def note_result(self, result: Mapping) -> None:
        """Adopt the server's answer for the epoch last built by
        :meth:`params_for`: on success the pending read becomes the
        confirmed base at the reported ``lag_epoch``; a ``resync``
        answer (or a missing stream section) drops the base so the next
        epoch re-seeds dense."""
        stream = (result or {}).get("stream") or {}
        if stream.get("resync") or "lag_epoch" not in stream:
            self.note_failure()
            return
        self._base = self._pending or self._base
        self._base_epoch = int(stream["lag_epoch"])
        self._pending = None

    def note_failure(self) -> None:
        """The request failed (error, drop, shed without a lag_epoch):
        the server's base is unknown — send dense next epoch."""
        self._base = None
        self._base_epoch = None
        self._pending = None


class AssignmentDeltaTracker:
    """Client-side reconstructor for DELTA RESPONSES (service.py
    "Delta responses") — the downlink mirror of
    :class:`LagDeltaTracker`: acks the assignment epoch it holds so
    the server may answer with only the changed rows
    (``result.assignment_delta``), then reconstructs the dense
    assignments dict bit-exactly from its held base.

    Usage, once per stream per epoch (composes with the lag tracker —
    both stamp fields onto the same params dict)::

        params = lag_tracker.params_for(rows)
        assign_tracker.stamp(params)            # adds assign_ack
        result = client.stream_assign(..., **params)
        assignments = assign_tracker.note_result(result, members)
        lag_tracker.note_result(result)

    The tracker acks nothing until a dense answer establishes a base
    (``stream.assign_epoch``); after that every answer either applies
    a delta against the held base (the server only serves one when the
    ack matched and the roster is unchanged — the same
    monotone-epoch/ack/resync ladder as the upload path) or is a dense
    re-seed.  Any failed request drops the ack
    (:meth:`note_failure`), so the next answer is dense — resync
    semantics identical to the lag tracker's."""

    def __init__(self):
        self._epoch: Optional[int] = None
        self._owner: Optional[Dict[int, str]] = None  # pid -> member
        self._topic: Optional[str] = None

    def stamp(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Add ``assign_ack`` for the held base (no-op before the
        first confirmed dense answer); returns ``params``."""
        if self._epoch is not None and self._owner is not None:
            params["assign_ack"] = self._epoch
        return params

    def note_result(
        self, result: Mapping, members: Sequence[str]
    ) -> Dict[str, Any]:
        """Adopt one ``stream_assign`` answer and return the dense
        assignments dict (reconstructed for a delta answer, adopted
        as-is for a dense one).  ``members`` is the member list the
        request named — owner indices in a delta bind to its sorted
        order, exactly as the server's dense dict does."""
        members_sorted = sorted(str(m) for m in members)
        stream = (result or {}).get("stream") or {}
        delta = (result or {}).get("assignment_delta")
        if delta is not None:
            if (
                self._owner is None
                or delta.get("base_epoch") != self._epoch
            ):
                # The server deltas only against an acked base; a
                # mismatch here means state desynchronized (client
                # bug, crossed responses) — drop the base and demand
                # dense next epoch rather than apply onto the wrong
                # view.
                self.note_failure()
                raise ValueError(
                    "assignment_delta names a base this tracker does "
                    "not hold; re-sync next epoch"
                )
            for pid, owner in zip(delta["indices"], delta["owners"]):
                self._owner[int(pid)] = members_sorted[int(owner)]
            self._epoch = int(delta["epoch"])
            self._topic = delta.get("topic", self._topic)
            return self.assignments(members_sorted)
        assignments = (result or {}).get("assignments")
        if assignments is None:
            self.note_failure()
            raise ValueError(
                "result carries neither assignments nor "
                "assignment_delta"
            )
        owner: Dict[int, str] = {}
        topic = self._topic
        for m, rows in assignments.items():
            for t, pid in rows:
                owner[int(pid)] = str(m)
                topic = t
        self._owner = owner
        self._topic = topic
        epoch = stream.get("assign_epoch")
        # An old server (no delta-response support) never confirms an
        # epoch — the tracker then acks nothing and behaves densely.
        self._epoch = int(epoch) if epoch is not None else None
        return assignments

    def assignments(self, members_sorted: Sequence[str]) -> Dict[str, Any]:
        """The held dense view, in the server's wire shape: ascending
        pids per member (the server appends rows in ascending-pid
        order, so reconstruction matches it bit-for-bit)."""
        out: Dict[str, Any] = {m: [] for m in members_sorted}
        for pid in sorted(self._owner or {}):
            out[self._owner[pid]].append([self._topic, pid])
        return out

    def note_failure(self) -> None:
        """The request failed: the server may have advanced its epoch
        without this client seeing the answer — drop the base so the
        next answer re-seeds dense."""
        self._epoch = None
        self._owner = None


def compute_partition_lag(
    partition_metadata: Optional[OffsetAndMetadata],
    begin_offset: int,
    end_offset: int,
    auto_offset_reset_mode: str,
) -> int:
    """Pure lag formula; exact parity with reference :376-404.

    lag = max(end_offset - next_offset, 0) where next_offset is the committed
    offset if present, else end_offset when auto.offset.reset=latest
    (case-insensitive), else begin_offset (earliest / none / anything else).
    """
    if partition_metadata is not None:
        next_offset = partition_metadata.offset
    elif auto_offset_reset_mode.lower() == "latest":
        next_offset = end_offset
    else:
        # assume earliest (reference :393-396: any non-"latest" mode,
        # including "none", takes the earliest branch)
        next_offset = begin_offset
    return max(end_offset - next_offset, 0)


class MetadataConsumer(Protocol):
    """The slice of KafkaConsumer the lag reader uses (reference :339-342).

    Three blocking batch RPCs per topic: ListOffsets (begin), ListOffsets
    (end), OffsetFetch (committed).  Exceptions are deliberately NOT caught —
    a broker failure must abort the rebalance, matching reference semantics
    (SURVEY §2.4.9).
    """

    def beginning_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]: ...

    def end_offsets(
        self, partitions: Sequence[TopicPartition]
    ) -> Mapping[TopicPartition, int]: ...

    def committed(
        self, partitions: Set[TopicPartition]
    ) -> Mapping[TopicPartition, Optional[OffsetAndMetadata]]: ...


def read_topic_partition_lags(
    metadata_consumer: MetadataConsumer,
    cluster: Cluster,
    all_subscribed_topics: Iterable[str],
    auto_offset_reset_mode: str = "latest",
    retry: Optional[LagRetryPolicy] = None,
) -> LagMap:
    """Fetch current consumer-group lag for every partition of every topic.

    Exact behavioral parity with reference :317-365:
    * topics with null/empty cluster metadata are warned about and excluded
      from the result map entirely (:358-360);
    * missing begin/end offsets for a partition default to 0 (:350-351);
    * ``committed`` may omit partitions or map them to None — both mean "no
      committed offset" (:349).

    ``retry`` (default None = reference abort semantics) bounds transient
    broker failures per RPC — see :class:`LagRetryPolicy`.  The fault
    points ``lag.begin`` / ``lag.end`` / ``lag.committed`` sit INSIDE the
    retried callables so injection drills exercise the retry path.
    """
    topic_partition_lags: Dict[str, List[TopicPartitionLag]] = {}
    # Client wire edge: called under the assignor's rebalance scope the
    # outer trace wins (flatten) and this only contributes the span;
    # called standalone (operator tooling, tests) it self-roots a
    # client-kind trace so lag reads are traceable on their own.
    with metrics.request_scope(kind="client", root_name="lag.read"):
        with metrics.span("lag.read"):
            _read_all(
                topic_partition_lags, metadata_consumer, cluster,
                all_subscribed_topics, auto_offset_reset_mode, retry,
            )
    return topic_partition_lags


def _read_all(
    topic_partition_lags, metadata_consumer, cluster,
    all_subscribed_topics, auto_offset_reset_mode, retry,
):
    for topic in all_subscribed_topics:
        partition_info = cluster.partitions_for_topic(topic)
        if not partition_info:
            LOGGER.warning(
                "Skipping assignment for topic %s since no metadata is available",
                topic,
            )
            continue

        topic_partitions = [
            TopicPartition(p.topic, p.partition) for p in partition_info
        ]
        rows: List[TopicPartitionLag] = []

        # The three batch RPCs — the only network boundary in the plugin.
        def _begin():
            faults.fire("lag.begin")
            return metadata_consumer.beginning_offsets(topic_partitions)

        def _end():
            faults.fire("lag.end")
            return metadata_consumer.end_offsets(topic_partitions)

        def _committed():
            faults.fire("lag.committed")
            return metadata_consumer.committed(set(topic_partitions))

        begin_offsets = _call_with_retry(_begin, "beginning_offsets", retry)
        end_offsets = _call_with_retry(_end, "end_offsets", retry)
        committed = _call_with_retry(_committed, "committed", retry)

        for tp in topic_partitions:
            lag = compute_partition_lag(
                committed.get(tp),
                begin_offsets.get(tp, 0),
                end_offsets.get(tp, 0),
                auto_offset_reset_mode,
            )
            rows.append(TopicPartitionLag(tp.topic, tp.partition, lag))
        topic_partition_lags[topic] = rows
