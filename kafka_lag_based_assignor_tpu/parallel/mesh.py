"""Compatibility shim: the topic-axis mesh backend moved to
:mod:`..sharded.topics` when multi-device became a first-class
subsystem (mesh manager, P-sharded solve, stream-sharded megabatch —
see :mod:`..sharded`).  Import from there; this module re-exports the
old names so existing callers keep working."""

from __future__ import annotations

from ..sharded.topics import (
    assign_global_replicated,
    assign_sharded,
    make_mesh,
    shard_topic_batch,
)

__all__ = [
    "assign_global_replicated",
    "assign_sharded",
    "make_mesh",
    "shard_topic_batch",
]
