"""Device-mesh sharding for multi-chip assignment."""
