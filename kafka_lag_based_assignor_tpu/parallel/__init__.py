"""Compatibility package: absorbed into :mod:`..sharded` (the
first-class multi-device backend).  ``parallel.mesh`` re-exports the
topic-axis API from :mod:`..sharded.topics`."""
