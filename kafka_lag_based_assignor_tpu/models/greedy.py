"""Host-side greedy LPT oracle — the reference-semantics ground truth.

This is the pure assignment core (layer L3 of the reference,
LagBasedPartitionAssignor.java:166-308) re-stated as a plain Python function.
It exists for three reasons:

1. **Oracle** for differential testing of the TPU kernels (bit-exact parity).
2. **Fallback** path so a rebalance never fails because the accelerator is
   unreachable (SURVEY §5, failure-detection row).
3. Executable specification of the semantics the kernels must reproduce
   (SURVEY §2.4): count-primary / lag-secondary / member-id-tertiary
   selection, lag-descending / partition-id-ascending processing order,
   per-topic independence, every member present in the output.

Unlike the reference, the input lag lists are NOT mutated (SURVEY §2.4.10
calls the in-place sort an implementation wart, not a contract).

Defined domain: per-topic TOTAL lag < 2**63.  Beyond that the Java
reference's ``long`` accumulator (reference :216-219, :266) silently wraps
— as do the device kernels' int64 totals — while this oracle's Python ints
keep exact counts, so bit-parity is only meaningful (and only asserted)
inside the int64 domain.  Kafka lags are message counts; real totals sit
many orders of magnitude below the bound.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

from ..types import AssignmentMap, TopicPartition, TopicPartitionLag


def consumers_per_topic(
    subscriptions: Mapping[str, Sequence[str]],
) -> Dict[str, List[str]]:
    """Invert member->topics into topic->members (reference :410-426).

    Member order within each topic list follows the iteration order of
    ``subscriptions`` — irrelevant to the result because selection ends in a
    total order over member ids (SURVEY §2.4.2).
    """
    result: Dict[str, List[str]] = {}
    for member_id, topics in subscriptions.items():
        for topic in topics:
            result.setdefault(topic, []).append(member_id)
    return result


def assign_topic_greedy(
    assignment: AssignmentMap,
    topic: str,
    consumers: Sequence[str],
    partition_lags: Sequence[TopicPartitionLag],
    total_lag: Dict[str, int] | None = None,
) -> None:
    """Greedy LPT for one topic, appended into ``assignment`` in place.

    Exact reference semantics (:204-308): process partitions in descending
    lag (ties: ascending partition id); each partition goes to the consumer
    minimizing (assigned count, total assigned lag, member id).

    ``total_lag`` defaults to a fresh all-zero accumulator — the reference's
    topic-local ``consumerTotalLags`` (:216, SURVEY §2.4.3).  Passing a
    shared dict (updated in place) carries the lag tiebreak across calls,
    which is how :func:`assign_greedy_global` implements the cross-topic
    quality mode; count stays topic-local (primary criterion) either way.
    """
    if not consumers:
        return

    if total_lag is None:
        total_lag = {m: 0 for m in consumers}
    total_count = {m: 0 for m in consumers}

    ordered = sorted(partition_lags, key=lambda p: (-p.lag, p.partition))
    for part in ordered:
        member = min(consumers, key=lambda m: (total_count[m], total_lag[m], m))
        assignment[member].append(TopicPartition(part.topic, part.partition))
        total_lag[member] += part.lag
        total_count[member] += 1


def assign_greedy_global(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
) -> AssignmentMap:
    """Cross-topic global-balance quality mode — host oracle/fallback.

    Beyond-reference feature (the reference keeps ``consumerTotalLags``
    local to each topic, :216, SURVEY §2.4.3).  Selection is still
    (per-TOPIC count, total lag, member id) — so the per-topic count
    invariant max − min ≤ 1 is preserved — but the lag totals accumulate
    across all topics **within a subscriber-set group** (topics whose
    subscriber sets are identical), mirroring exactly the scope the device
    kernel's carried scan covers (:func:`..ops.rounds_kernel.assign_global_rounds`
    via :func:`..ops.packing.build_groups`).  Topics are processed in global
    sorted order with one shared accumulator per group, so per-member list
    order matches the device dispatch path bit-for-bit.
    """
    assignment: AssignmentMap = {member: [] for member in subscriptions}
    by_topic = consumers_per_topic(subscriptions)

    # Topics in global sorted order (the same append order as assign_greedy
    # and the device dispatch), with one shared lag accumulator per
    # subscriber-set group — totals only ever interact within a group, so
    # interleaving groups is equivalent to processing them separately.
    group_totals: Dict[tuple, Dict[str, int]] = {}
    for topic in sorted(by_topic):
        members = tuple(sorted(set(by_topic[topic])))
        if not members or not partition_lag_per_topic.get(topic):
            continue
        totals = group_totals.setdefault(members, {m: 0 for m in members})
        assign_topic_greedy(
            assignment,
            topic,
            members,
            partition_lag_per_topic[topic],
            total_lag=totals,
        )
    return assignment


def host_fallback_for(solver: str):
    """The host solver used by both the in-process plugin adapter and the
    sidecar service when a device solve fails or times out.

    Exactness of the fallback depends on the solver: ``global`` keeps its
    semantics exactly (:func:`assign_greedy_global` is the same algorithm
    on host); the reference-parity kernels (``rounds``/``scan``/``native``)
    fall back to :func:`assign_greedy`, which is bit-identical to them.
    ``sinkhorn`` has no host equivalent — its fallback is
    :func:`assign_greedy`, a *quality downgrade* (OT-optimized balance ->
    4/3-approximation greedy) that still satisfies every invariant
    (count spread <= 1, determinism).  Callers see the downgrade via
    ``RebalanceStats.fallback_used`` plus the warning log."""
    return assign_greedy_global if solver == "global" else assign_greedy


def assign_greedy(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
) -> AssignmentMap:
    """The pure core: (topic lags, member subscriptions) -> member assignments.

    Parity points with reference :166-188:
    * every member appears in the output, possibly with an empty list (:171-174);
    * topics missing from the lag map assign nothing (:182);
    * topics are independent — lag is never balanced across topics (§2.4.3).

    Topics are processed in sorted order for run-to-run determinism of the
    *per-member partition list order* (the reference's order depends on
    HashMap iteration; the assignment *content* is order-independent).
    """
    assignment: AssignmentMap = {member: [] for member in subscriptions}
    by_topic = consumers_per_topic(subscriptions)
    for topic in sorted(by_topic):
        assign_topic_greedy(
            assignment,
            topic,
            by_topic[topic],
            partition_lag_per_topic.get(topic, ()),
        )
    return assignment
