"""Sinkhorn-style optimal-transport relaxation solver (implicit plan).

The greedy LPT core (reference semantics) is a 4/3-approximation for makespan
and is what the reference prescribes; this solver is the framework's
*quality* alternative (SURVEY §7 step 5; BASELINE config 4 compares the two
on heavy skew): it directly optimizes the north-star metric — max/mean lag
imbalance — while preserving the count-primary invariant
``max - min assigned partitions <= 1``.

Method: entropic mirror descent on the squared-load objective over the
transport polytope, with Sinkhorn-style alternating marginal scaling
(pattern references: the OT papers in PAPERS.md — FlashSinkhorn's
tile-streaming iteration, push-relabel additive approximation for rounding
intuition; patterns only, no code).

* relaxation variable  X in [0,1]^{P x C}, row-stochastic: X[p] is a
  distribution of partition p over consumers;
* objective  sum_j load_j^2  with  load_j = sum_p lag_p X[p,j]  — minimized
  exactly when loads are equal;
* update     X <- X * exp(-eta * ws_p * (load_j - mean load))  (mirror /
  multiplicative-weights step on the centered gradient, ws = lag/scale),
  followed by one Sinkhorn pair: column scaling toward the balanced count
  marginal P/C, then row re-normalization;
* rounding   partitions in descending-lag order pick the least-loaded open
  consumer (capacities floor/ceil(P/C)) with the plan as a continuous
  tie-break bonus — integral, count-balanced by construction — then a
  pairwise-exchange refinement pass (:mod:`..ops.refine`).

**TPU-native key idea — the plan is never materialized.**  Every update
above is rank-structured, so by induction the log-plan stays exactly

    logX[p, j] = -ws_p * A_j + B_j   (+ row normalizer)

where ``A`` accumulates the mirror steps and ``B`` the column corrections —
the row normalizer cancels in the row softmax.  The iteration state is two
f32[C] vectors instead of a [P, C] matrix (524 MB at the 100k x 1k north
star), and — since rows with equal ``ws`` are identical — each iteration
needs only the plan's two marginal statistics over the DEDUPLICATED
lag-value axis, computed by the fused tile-streaming kernel in
:mod:`..ops.plan_stats` (Pallas on TPU, tiled lax elsewhere).  Symmetry
is broken by a deterministic hash seed in ``B0``; per-(p, j) hash noise
remains only as the rounding tie-break.

**Quality guarantee:** the returned assignment is the better (by max
consumer load) of the refined OT rounding and the plain greedy rounds
kernel — the quality mode never loses to greedy.
"""

from __future__ import annotations

import functools
from typing import Mapping, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.plan_stats import (
    _pallas_available,
    implicit_plan_argmax,
    implicit_plan_rows,
    noise,
    plan_stats,
)
from ..types import AssignmentMap, TopicPartitionLag

# At or below this many partition rows the sequential rounding scan (one
# step per partition) is cheap and slightly better-steered than the
# parallel rounding; above it the scan's P sequential steps dominate wall
# time, so the parallel argmax+repair rounding takes over
# (see _round_parallel).  The refinement pass equalizes final quality
# between the two (measured: identical imbalance at BASELINE config 2).
_SCAN_ROUNDING_MAX_P = 4096

# Auto refinement budgets per rounding path (used when the caller passes
# refine_iters=None): the parallel rounding starts coarser than the scan,
# so it gets a larger floor.  An EXPLICIT refine_iters is always honored
# exactly (utils/config.REFINE_ITERS_CONFIG documents the auto rule).
_AUTO_REFINE_SCAN = 24
_AUTO_REFINE_PARALLEL = 96

# Refine-start selection (see _assign_topic_sinkhorn_jit): the OT rounding
# is refined only while its peak load is within this factor of greedy's;
# beyond it the rounding is too far gone for the budget and the refine
# runs from greedy's start instead (where the patience stop exits fast).
_START_SLACK = 3


def _scale_np(lags: np.ndarray, valid: np.ndarray, C: int) -> float:
    """Host half of THE scale definition: ideal per-consumer load
    ``max(total valid lag, 1) / C``.  Must stay the same formula AND the
    same accumulation dtype as :func:`_scaled_ws` (the traced half) — the
    dedup identity requires the host-aggregated ``ws_u`` and the traced
    per-row ``ws`` to describe the same normalization (pinned by
    test_plan_stats.py).  Both halves accumulate the total in float64
    (numpy's int64 sum here; an f64 ``jnp.sum`` there — x64 mode is
    mandatory, ops/dispatch.ensure_x64) and divide in float64 before the
    final f32 cast: bit-identical whenever the total lag stays below
    2^53 (every f64 partial sum is then exact regardless of XLA's
    reduction order), and within one f64 reduction rounding beyond —
    versus wholesale f32-accumulation drift before this was unified."""
    return max(float(lags[valid].sum()), 1.0) / C


def _scaled_ws(lags: jax.Array, valid: jax.Array, C: int) -> jax.Array:
    """Traced half of THE scale definition (see :func:`_scale_np`):
    f32 per-row scaled lags, invalid rows 0.  The sum/divide run in f64 to
    match the host half's accumulation exactly."""
    w = jnp.where(valid, lags, 0).astype(jnp.float64)
    scale = jnp.maximum(jnp.sum(w), 1.0) / C
    return (w / scale).astype(jnp.float32)


def _require_concrete(lags, valid, caller: str) -> None:
    """Enforce the HOST-ONLY input contract of the public Sinkhorn entry
    points: the dedup aggregation (:func:`_dedup_weights`) runs in numpy on
    concrete values, so these functions cannot be called with tracers —
    i.e. from inside ``jit``/``vmap``/``grad``.  Without this check a
    traced call fails deep inside ``np.unique`` with an opaque
    TracerArrayConversionError; with it, the contract violation is named
    at the boundary."""
    for name, x in (("lags", lags), ("valid", valid)):
        if isinstance(x, jax.core.Tracer):
            raise TypeError(
                f"{caller} is host-only (its dedup pre-pass runs in numpy) "
                f"and was called under a JAX trace with {name}= a tracer; "
                "call it outside jit, or use the jitted inner "
                "_assign_topic_sinkhorn_jit with host-prepared dedup "
                "weights instead"
            )


# Cap on the deduplicated value axis fed to the duals iteration.  With
# near-distinct lags (U ~ P — e.g. Zipf at the 100k north star) a plain
# dedup degenerates: each of ~24 duals iterations streams a [U, C] logits
# plan twice, and the quality mode's latency collapses (measured 17.5 s at
# 100k x 1k on the CPU backend, BENCH_r04).  Above the cap the tail of the
# value distribution is LOG-BUCKETED (below: exact top values + log-spaced
# bins at <=2.8% relative width): each bin is represented by its weighted
# MEAN value, so both marginal statistics stay exactly mass-preserving
# (sum count, sum ws are unchanged); only the within-bin variation of the
# plan rows is approximated — a sub-3% logits perturbation that steers the
# mirror descent imperceptibly, and whose residual the exchange-refinement
# pass absorbs (the rounding itself always uses EXACT per-row ws).
_DEDUP_CAP = 4096
# How many of the largest unique values stay exact above the cap: the top
# of the lag distribution carries most of the load mass (Zipf), so it is
# excluded from bucketing entirely.
_DEDUP_EXACT_TOP = _DEDUP_CAP // 2


def _quantize_tail(uniq: np.ndarray, counts: np.ndarray):
    """Aggregate (uniq asc, counts) onto <= _DEDUP_CAP representative
    values: the _DEDUP_EXACT_TOP largest stay exact; the tail maps onto
    log-spaced bins (plus a dedicated bin for value 0), each represented
    by its weighted mean.  Returns (vals, counts, vsums) with
    vsums[i] == sum of (value * count) over the bin — exact, so the
    aggregate mass the duals iteration sees is unchanged."""
    split = len(uniq) - _DEDUP_EXACT_TOP
    head_v, head_c = uniq[split:], counts[split:]
    tail_v, tail_c = uniq[:split], counts[:split]
    nbins = _DEDUP_CAP - _DEDUP_EXACT_TOP
    pos = tail_v > 0
    lo = float(tail_v[pos].min()) if pos.any() else 1.0
    hi = float(tail_v.max())
    if hi <= lo:
        edges = np.array([lo], dtype=np.float64)
    else:
        # nbins-1 interior edges over [lo, hi]; ratio (hi/lo)^(1/(nbins-1))
        # bounds each bin's relative width (<= 2.8% for a 2^53 range at
        # the default cap).
        edges = np.geomspace(lo, hi, num=nbins - 1)
    # Bin 0 collects value 0 (and anything below the first edge).  All
    # products run in f64: int64 value*count could wrap for huge lags
    # (f64 only rounds, which the downstream f32 cast does anyway).
    idx = np.digitize(tail_v, edges)
    cnt_b = np.bincount(idx, weights=tail_c.astype(np.float64),
                        minlength=nbins)
    vsum_b = np.bincount(
        idx,
        weights=tail_v.astype(np.float64) * tail_c.astype(np.float64),
        minlength=nbins,
    )
    nz = cnt_b > 0
    rep_b = np.zeros_like(vsum_b)
    rep_b[nz] = vsum_b[nz] / cnt_b[nz]
    head_vf = head_v.astype(np.float64)
    head_cf = head_c.astype(np.float64)
    vals = np.concatenate([rep_b[nz], head_vf])
    cnts = np.concatenate([cnt_b[nz], head_cf])
    vsums = np.concatenate([vsum_b[nz], head_vf * head_cf])
    return vals, cnts, vsums


def _dedup_weights(lags: np.ndarray, valid: np.ndarray, C: int):
    """Host-side aggregation onto the unique-lag-value axis.

    Partitions with equal scaled lag have identical (noise-free) plan rows,
    so the duals iteration only needs per-unique-value weights
    (plan_stats module docstring).  Above ``_DEDUP_CAP`` unique values the
    tail is log-bucketed (see :func:`_quantize_tail`) so the iteration
    cost is bounded regardless of how distinct the lags are.  Padded to
    the power-of-two bucket so the jit cache stays bounded as U drifts;
    padding rows carry count=wsum=0 and contribute exactly nothing.

    Returns (ws_u f32[U_pad], count_u f32[U_pad], wsum_u f32[U_pad]).
    """
    from ..ops.packing import pad_bucket

    vals = lags[valid]
    scale = _scale_np(lags, valid, C)
    uniq, counts = np.unique(vals, return_counts=True)
    if len(uniq) > _DEDUP_CAP:
        vals_r, cnts_r, vsums_r = _quantize_tail(uniq, counts)
    else:
        vals_r = uniq.astype(np.float64)
        cnts_r = counts.astype(np.float64)
        vsums_r = vals_r * cnts_r
    U = max(len(vals_r), 1)
    U_pad = pad_bucket(U)
    ws_u = np.zeros(U_pad, np.float32)
    count_u = np.zeros(U_pad, np.float32)
    wsum_u = np.zeros(U_pad, np.float32)
    ws_u[: len(vals_r)] = vals_r / scale
    count_u[: len(vals_r)] = cnts_r
    wsum_u[: len(vals_r)] = vsums_r / scale
    return ws_u, count_u, wsum_u


def sinkhorn_duals(
    lags,
    valid,
    num_consumers: int,
    iters: int = 24,
    eta: float = 8.0,
):
    """Run the implicit-plan iteration; returns ``(A, B, ws)``.

    HOST-ONLY: ``lags``/``valid`` must be concrete arrays (numpy or
    committed jax arrays), never tracers — the dedup pre-pass runs in
    numpy (enforced by :func:`_require_concrete`).

    ``A``/``B`` are the f32[C] state vectors of the rank-structured
    log-plan; ``ws`` the f32[P] scaled lags (lag / ideal-per-consumer-load).
    Plan rows can be materialized on demand with
    :func:`..ops.plan_stats.implicit_plan_rows`.
    """
    # Resolve the Pallas-vs-lax choice EAGERLY: inside the trace below the
    # probe could not execute (a lowering failure would abort the compile
    # with no fallback, see plan_stats._pallas_available).
    _pallas_available()
    _require_concrete(lags, valid, "sinkhorn_duals")
    lags_np = np.asarray(lags)
    valid_np = np.asarray(valid)
    C = int(num_consumers)
    ws_u, count_u, wsum_u = _dedup_weights(lags_np, valid_np, C)
    A, B = _sinkhorn_duals_jit(
        ws_u, count_u, wsum_u, num_consumers=C, iters=iters, eta=eta
    )
    return A, B, _scaled_ws(lags, valid, C)


@functools.partial(jax.jit, static_argnames=("num_consumers", "iters"))
def _sinkhorn_duals_jit(
    ws_u: jax.Array,
    count_u: jax.Array,
    wsum_u: jax.Array,
    num_consumers: int,
    iters: int = 24,
    eta: float = 8.0,
    tol: float = 2e-5,
):
    """Damped mirror-descent / Sinkhorn iteration with a convergence
    early-exit.

    Two changes over the fixed-step fori_loop this replaces (both
    measured on the 100k x 1k north star, where the fixed step
    OSCILLATED — load spread stuck at ~3.2 across all 24 iterations):

    * **epsilon-scaled step** — the mirror step's effective rate is
      ``eta * scale`` with ``scale`` halved whenever the load spread GREW
      since the previous iteration (overshoot) and recovered by 1.2x
      (capped at 1) while progress is monotone.  Monotone instances see
      the exact fixed-eta trajectory (scale stays 1); the oscillating
      north star converges to spread ~4e-3 in the same 24 iterations.
    * **convergence early-exit** — the loop stops once BOTH residuals are
      tiny: the load spread (mean load is 1 in ws units, so absolute ==
      relative) AND the column-marginal correction ``max |log(cap /
      colsum)|``.  Watching both matters: a column-only test exits at
      iteration ~2 on heavy-skew inputs with the loads far from
      converged (measured when a B-only exit was attempted and
      reverted; pinned by test_duals_converge_on_heavy_skew).  The
      heavy-skew profile now exits after ~6 of its 24 budgeted
      iterations at spread ~1e-5, well inside the pinned 1e-4.

    ``iters`` stays the hard budget; the jitted executable is cached per
    (U_pad, C, iters) and reused across calls.
    """
    C = int(num_consumers)
    n_valid = jnp.maximum(jnp.sum(count_u), 1.0)
    cap = n_valid / C  # balanced count marginal

    eta32 = jnp.float32(eta)

    def body(state):
        i, scale, prev_spread, _, A, B = state
        # Mirror step on d/dX sum_j load_j^2 ∝ ws_p * load_j, centered so
        # the step is invariant to uniform load shifts.  load is already in
        # ws units (= absolute load / scale).
        load, _ = plan_stats(ws_u, count_u, wsum_u, A, B, need="load")
        spread = jnp.max(load) - jnp.min(load)
        grew = spread > prev_spread
        scale = jnp.where(
            grew,
            scale * jnp.float32(0.5),
            jnp.minimum(scale * jnp.float32(1.2), jnp.float32(1.0)),
        )
        A = A + eta32 * scale * (load - jnp.mean(load))
        # Sinkhorn pair: scale columns toward the balanced count marginal
        # (rows re-normalize implicitly in the softmax).
        _, colsum = plan_stats(
            ws_u, count_u, wsum_u, A, B, need="colsum"
        )
        upd = jnp.log(cap / (colsum + jnp.float32(1e-9)))
        B = B + upd
        delta = jnp.maximum(spread, jnp.max(jnp.abs(upd)))
        return i + 1, scale, spread, delta, A, B

    def cond(state):
        i, delta = state[0], state[3]
        return (i < iters) & (delta > jnp.float32(tol))

    A0 = jnp.zeros((C,), jnp.float32)
    # Symmetry-breaking seed: the noise-free iteration has a symmetric
    # fixpoint (all consumers identical => zero gradient); a tiny
    # deterministic per-consumer offset in B0 breaks it, replacing the
    # per-(p, j) noise the deduplicated stats no longer carry.
    B0 = noise(
        jnp.zeros((C,), jnp.int32), jnp.arange(C, dtype=jnp.int32)
    )
    inf32 = jnp.float32(jnp.inf)
    _, _, _, _, A, B = lax.while_loop(
        cond, body, (jnp.int32(0), jnp.float32(1.0), inf32, inf32, A0, B0)
    )
    return A, B


def _round_parallel(lags, ws, valid, A, B, C: int, floor_cap, extras,
                    cap_vec=None, cap_max=None):
    """Parallel (O(P log P), no per-partition scan) plan rounding.

    ``cap_vec`` (int32[C] summing to the valid row count) replaces the
    uniform floor/ceil capacities with EXPLICIT per-consumer seat
    counts — the federated weighted-shard rounding (ops/fedsolve) seats
    capacity-proportional counts this way; ``cap_max`` must then bound
    its largest entry (STATIC: it sizes the open-slot enumeration).

    1. each partition takes its plan-argmax consumer (tiled, parallel);
    2. capacity repair: within each consumer's takers (sorted lag desc) the
       first cap_j keep their seat — the plan is near-balanced after the
       Sinkhorn iteration, so few overflow;
    3. the overflow re-seats positionally: the k-th largest-lag overflow
       partition takes the k-th open slot, slots ordered round-robin over
       consumers by ascending kept load (a one-shot round decomposition —
       each "round" hands every open consumer one partition, lightest
       first).  Count spread <= 1 holds by construction; the exchange
       refinement pass afterwards re-tightens lag balance.

    Returns choice int32[P] (input order, -1 for invalid rows).
    """
    P = ws.shape[0]
    if cap_vec is None:
        cap = floor_cap + (jnp.arange(C, dtype=jnp.int32) < extras).astype(
            jnp.int32
        )  # int32[C], sums to n_valid
    else:
        cap = cap_vec.astype(jnp.int32)

    # Noise-FREE argmax: the per-(p, j) hash tie-break costs ~8 int ops
    # per logit (~2/3 of the whole [P, C] pass at the 100k north star)
    # and only decides which consumer equal-ws rows pile onto — ties the
    # capacity repair below redistributes positionally anyway, so the
    # hash buys nothing this path keeps.  C sentinel for invalid rows.
    jstar = implicit_plan_argmax(ws, valid, A, B, tie_noise=False)

    # Group rows by (consumer, lag desc); sentinel group sorts last.
    neg_lag = jnp.where(valid, -lags, jnp.iinfo(lags.dtype).max)
    idx = jnp.arange(P, dtype=jnp.int32)
    _, _, perm = lax.sort((jstar, neg_lag, idx), num_keys=2)
    sj = jstar[perm]
    # Consumer-segment boundaries in the sorted order: one searchsorted
    # with C+1 scalar queries serves the keep test, the kept counts
    # (min(segment length, cap)) and the kept loads (masked cumsum +
    # boundary differences) — no P-sized scatters.
    bnd = jnp.searchsorted(
        sj, jnp.arange(C + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    pos = idx - bnd[jnp.clip(sj, 0, C)]
    keep = (sj < C) & (pos < cap[jnp.clip(sj, 0, C - 1)])

    ws_s = ws[perm]
    kept_cnt = jnp.minimum(bnd[1:] - bnd[:-1], cap)
    csum = jnp.concatenate(
        [jnp.zeros((1,), jnp.float32),
         jnp.cumsum(jnp.where(keep, ws_s, 0.0))]
    )
    kept_load = csum[bnd[1:]] - csum[bnd[:-1]]
    rem = cap - kept_cnt  # open seats per consumer, >= 0

    # Open slots in (round, load-rank) order: slot (j, r) exists iff
    # r < rem_j; lighter consumers seat first within a round.
    load_rank = jnp.zeros((C,), jnp.int32).at[
        jnp.argsort(kept_load).astype(jnp.int32)
    ].set(jnp.arange(C, dtype=jnp.int32))
    cap_max = int(cap_max) if cap_max is not None else P // C + 1
    slot_r = jnp.repeat(
        jnp.arange(cap_max, dtype=jnp.int32)[:, None], C, axis=1
    ).reshape(-1)
    slot_j = jnp.repeat(
        jnp.arange(C, dtype=jnp.int32)[None, :], cap_max, axis=0
    ).reshape(-1)
    slot_open = slot_r < rem[slot_j]
    slot_key = jnp.where(
        slot_open,
        slot_r * jnp.int32(C) + load_rank[slot_j],
        jnp.iinfo(jnp.int32).max,
    )
    _, slot_j_sorted = lax.sort((slot_key, slot_j), num_keys=1)

    # Overflow rows in lag-desc order meet slots positionally.
    overflow = valid[perm] & ~keep
    okey = jnp.where(overflow, neg_lag[perm], jnp.iinfo(lags.dtype).max)
    _, oorder = lax.sort((okey, idx), num_keys=1)
    n_over = jnp.sum(overflow.astype(jnp.int32))
    seat = jnp.where(
        idx < n_over, slot_j_sorted[jnp.minimum(idx, C * cap_max - 1)], -1
    )
    # Both remaining placements are permutation scatters; route them
    # through the backend-conditional inversion (sort-based on
    # accelerators, scatter on CPU — ops/sortops.unsort).
    from ..ops.sortops import unsort

    choice_sorted = jnp.maximum(
        jnp.where(keep, sj, -1), unsort(oorder, seat)
    )
    return unsort(perm, choice_sorted)


def assign_topic_sinkhorn(
    lags,
    partition_ids,
    valid,
    num_consumers: int,
    iters: int = 24,
    refine_iters: Optional[int] = None,
):
    """Integral, count-balanced assignment from the implicit Sinkhorn plan.

    HOST-ONLY entry point (see :func:`_require_concrete`): the dedup
    pre-pass runs in numpy, so this cannot be called under a JAX trace.

    Rounding (path chosen by size, ``_SCAN_ROUNDING_MAX_P``): partitions in
    descending-lag order pick the *least-loaded* open consumer (capacity
    floor/ceil(n/C)) with the plan row as a continuous tie-break bonus —
    LPT steered by the OT relaxation — or, for large topics, the parallel
    argmax+repair rounding.  A pairwise-exchange refinement pass
    (:mod:`..ops.refine`) then tightens max/mean imbalance.
    ``refine_iters=None`` selects the per-path auto budget
    (``_AUTO_REFINE_SCAN`` / ``_AUTO_REFINE_PARALLEL``); an explicit value
    is honored exactly.

    **Quality guarantee (portfolio):** the greedy rounds kernel runs as
    well (its cost is dwarfed by the duals iteration), and whichever
    assignment has the smaller maximum consumer load is returned — the
    quality mode can steer better than greedy where slack exists
    (BASELINE config 2) but can never return something worse (config 4,
    where greedy is already at the optimum plateau).

    Same output contract as the greedy kernels: (choice int32[P] in input
    order, counts int32[C], totals[C]).
    """
    _pallas_available()  # resolve kernel choice eagerly, outside the trace
    _require_concrete(lags, valid, "assign_topic_sinkhorn")
    C = int(num_consumers)
    # Quality-mode selection (ops/dispatch, ``tpu.assignor.quality.mode``):
    # when the dispatch layer elects the linear-space O(P + C) mode for
    # this shape — explicitly pinned, or "auto" at row counts where the
    # dense [U, C] streams stop fitting — the solve is served by
    # ops/linear_ot under the SAME output contract, so every existing
    # caller picks it up with no API change.
    from ..ops.dispatch import resolve_quality_mode

    if resolve_quality_mode(lags.shape[0], C) == "linear":
        from ..ops.linear_ot import assign_topic_linear

        return assign_topic_linear(
            lags, partition_ids, valid, num_consumers=C,
            iters=iters, refine_iters=refine_iters,
        )
    from ..utils import metrics

    metrics.REGISTRY.counter(
        "klba_quality_solve_total", {"mode": "sinkhorn"}
    ).inc()
    ws_u, count_u, wsum_u = _dedup_weights(
        np.asarray(lags), np.asarray(valid), C
    )
    if refine_iters is None:
        P = lags.shape[0]
        refine_iters = (
            _AUTO_REFINE_PARALLEL
            if P > _SCAN_ROUNDING_MAX_P
            else _AUTO_REFINE_SCAN
        )
    return _assign_topic_sinkhorn_jit(
        lags, partition_ids, valid, ws_u, count_u, wsum_u,
        num_consumers=num_consumers, iters=iters, refine_iters=refine_iters,
    )


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "refine_iters")
)
def _assign_topic_sinkhorn_jit(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    ws_u: jax.Array,
    count_u: jax.Array,
    wsum_u: jax.Array,
    num_consumers: int,
    iters: int,
    refine_iters: int,
):
    C = int(num_consumers)
    A, B = _sinkhorn_duals_jit(
        ws_u, count_u, wsum_u, num_consumers=C, iters=iters
    )
    ws = _scaled_ws(lags, valid, C)
    return _round_refine_portfolio(
        lags, partition_ids, valid, ws, A, B,
        num_consumers=C, refine_iters=refine_iters,
    )


def _round_refine_portfolio(
    lags, partition_ids, valid, ws, A, B, *,
    num_consumers: int, refine_iters: int,
):
    """Shared rounding + refine + portfolio tail of BOTH quality modes
    (called inside the Sinkhorn jit above and the linear mode's
    :func:`..ops.linear_ot._finish_linear_jit`): round the implicit
    plan described by the ``(A, B)`` duals, refine the more promising
    start, and never return worse than greedy.  Every buffer here is
    [P]- or [C, M]-shaped — O(P + C) live memory — which is what lets
    the linear mode share it unchanged."""
    from ..ops.rounds_kernel import assign_topic_rounds

    from ..ops.sortops import segment_sum

    C = int(num_consumers)
    P = lags.shape[0]

    n_valid = jnp.sum(valid.astype(jnp.int32))
    floor_cap = n_valid // C
    extras = n_valid - floor_cap * C  # this many consumers may hit ceil

    if P > _SCAN_ROUNDING_MAX_P:
        # Large topics: the per-partition scan below would dominate wall
        # time; round in parallel and lean on the refinement pass.
        choice = _round_parallel(
            lags, ws, valid, A, B, C, floor_cap, extras
        )
    else:
        neg_lag = jnp.where(valid, -lags, jnp.iinfo(lags.dtype).max)
        order = jnp.argsort(neg_lag).astype(jnp.int32)  # lag desc, pad last

        def step(carry, p):
            counts, totals, extras_left = carry
            is_valid = valid[p]
            # A consumer is open if under floor cap, or at floor cap while
            # ceil-slots remain.
            under_floor = counts < floor_cap
            at_floor = (counts == floor_cap) & (extras_left > 0)
            open_mask = under_floor | at_floor
            # Least (scaled) load first; the plan row contributes a
            # sub-unit bonus so it decides ties without overriding the
            # load ordering.
            xrow = implicit_plan_rows(p[None], ws[p][None], A, B)[0]
            score = totals - jnp.float32(0.01) * xrow
            score = jnp.where(open_mask, score, jnp.inf)
            who = jnp.argmin(score).astype(jnp.int32)
            take = is_valid
            one_hot = (jnp.arange(C, dtype=jnp.int32) == who) & take
            used_extra = take & at_floor[who]
            counts = counts + one_hot.astype(jnp.int32)
            totals = totals + jnp.where(one_hot, ws[p], 0.0)
            extras_left = extras_left - used_extra.astype(jnp.int32)
            return (counts, totals, extras_left), jnp.where(take, who, -1)

        init = (
            jnp.zeros((C,), jnp.int32),
            jnp.zeros((C,), jnp.float32),
            extras,
        )
        (_, _, _), sorted_choice = lax.scan(step, init, order)
        choice = jnp.full((P,), -1, jnp.int32).at[order].set(sorted_choice)

    # Refine the more PROMISING start, not unconditionally the OT rounding.
    # Measured trade (BENCH_DETAILS r3->r4): on configs where the OT
    # structure matters (zipf), refining the OT rounding reaches the
    # count-constrained optimum exactly even though its pre-refine max is
    # somewhat above greedy's; but on heavy skew the parallel rounding can
    # start an order of magnitude above greedy, and grinding it down burns
    # ~all of the refine budget only for the portfolio to return greedy
    # anyway.  So: refine the OT start only while its peak is within
    # _START_SLACK of greedy's; otherwise refine greedy's start, which on
    # those instances sits at/near the optimum plateau — the peak
    # stagnates immediately and the refine loop's patience stop exits
    # after a few rounds instead of the full budget.
    g_choice, g_counts, g_totals = assign_topic_rounds(
        lags, partition_ids, valid, num_consumers=C
    )
    ot_totals = segment_sum(
        jnp.where(valid, lags, 0), jnp.where(valid, choice, -1), C
    )
    use_ot_start = jnp.max(ot_totals) <= _START_SLACK * jnp.max(g_totals)
    start = jnp.where(use_ot_start, choice, g_choice)

    # Resident-table refine (ops/refine): bit-identical exchanges to
    # refine_assignment's exact-argmin semantics at O(K*M log M) per
    # round instead of two P-sized sorts — the stage that dominated the
    # quality mode's 8.2 s north-star latency (VERDICT r5 item 5).  Both
    # candidate starts are count-balanced, so the [C, M] table admits
    # them by construction.
    from ..ops.packing import table_rows
    from ..ops.refine import build_choice_tables, refine_rounds_resident

    row_tab, r_counts, r_totals = build_choice_tables(
        lags, valid, start, C, table_rows(P, C)
    )
    # Pair width capped at 64: from a near-optimal OT start the peak
    # repair happens in the top pairs, and the per-round slice work
    # scales with K — C//2 = 500 pairs at the north star made this
    # stage 3.0 s of the quality mode's 4.8 s for no measurable
    # imbalance gain over K=64 (rotation still reaches every partner
    # across the round budget).
    s_choice, _, s_counts, s_totals, _, _ = refine_rounds_resident(
        lags, start, row_tab, r_counts, r_totals, num_consumers=C,
        iters=refine_iters, max_pairs=min(C // 2, 64),
    )

    # Portfolio: never return worse than greedy.  Greedy's cost (one sort +
    # ceil(P/C) rounds) is negligible next to the duals iteration, and on
    # instances where greedy already sits at the optimum plateau (heavy
    # skew, BASELINE config 4) the OT rounding cannot reach it.
    use_s = jnp.max(s_totals) < jnp.max(g_totals)
    return (
        jnp.where(use_s, s_choice, g_choice),
        jnp.where(use_s, s_counts, g_counts),
        jnp.where(use_s, s_totals, g_totals),
    )


def assign_sinkhorn(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    iters: int = 24,
    refine_iters: Optional[int] = None,
) -> AssignmentMap:
    """Map-level Sinkhorn solve (same surface as
    :func:`..ops.dispatch.assign_device`); per-topic independence preserved.

    ``iters``/``refine_iters`` are the quality-vs-latency knobs, exposed
    through the config layer as ``tpu.assignor.sinkhorn.iters`` /
    ``tpu.assignor.refine.iters``."""
    from ..ops.dispatch import assign_per_topic, ensure_x64
    from ..ops.packing import pad_topic_rows

    ensure_x64()

    def solve_topic(lags, pids, num_consumers):
        lags_p, pids_p, valid = pad_topic_rows(lags, pids)
        choice, _, _ = assign_topic_sinkhorn(
            lags_p, pids_p, valid, num_consumers=num_consumers,
            iters=iters, refine_iters=refine_iters,
        )
        return choice

    return assign_per_topic(
        partition_lag_per_topic, subscriptions, solve_topic
    )
