"""Sinkhorn-style optimal-transport relaxation solver.

The greedy LPT core (reference semantics) is a 4/3-approximation for makespan
and is what the reference prescribes; this solver is the framework's
*quality* alternative (SURVEY §7 step 5; BASELINE config 4 compares the two
on heavy skew): it directly optimizes the north-star metric — max/mean lag
imbalance — while preserving the count-primary invariant
``max - min assigned partitions <= 1``.

Method: entropic mirror descent on the squared-load objective over the
transport polytope, with Sinkhorn-style alternating marginal scaling
(pattern references: the OT papers in PAPERS.md — FlashSinkhorn's
tile-friendly iteration, push-relabel additive approximation for rounding
intuition; patterns only, no code).

* relaxation variable  X in [0,1]^{P x C}, row-stochastic: X[p] is a
  distribution of partition p over consumers;
* objective  sum_j load_j^2  with  load_j = sum_p lag_p X[p,j]  — minimized
  exactly when loads are equal;
* update     X <- X * exp(-eta * lag_p * (load_j - mean load) / scale)
  (mirror/multiplicative-weights step on the gradient), followed by one
  Sinkhorn pair: column scaling toward the balanced count marginal P/C,
  then row re-normalization;
* rounding   partitions in descending-lag order pick their argmax-X
  consumer among those with remaining count capacity (capacities
  floor/ceil(P/C)), a lax.scan with a masked vectorized argmax — integral,
  count-balanced by construction.

Everything is [P, C] dense elementwise + row/col reductions — ideal XLA
fusion shape — and the iteration count is static (lax.fori_loop), so one
compiled program serves every rebalance at a bucketed shape.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..types import AssignmentMap, TopicPartitionLag


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters")
)
def sinkhorn_plan(
    lags: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    eta: float = 8.0,
):
    """Relaxed transport plan X [P, C] (rows of padding are uniform)."""
    C = int(num_consumers)
    P = lags.shape[0]
    w = jnp.where(valid, lags, 0).astype(jnp.float32)
    total = jnp.maximum(jnp.sum(w), 1.0)
    scale = total / C  # ideal per-consumer load
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    # Keep everything float32 (x64 mode would otherwise promote the carry).
    cap = n_valid.astype(jnp.float32) / C  # balanced count marginal

    # Symmetry breaking: from an exactly-uniform init every consumer is
    # identical and mirror descent preserves the symmetry forever (the
    # relaxed fixpoint is any row-stochastic plan with equal loads) — a tiny
    # deterministic perturbation lets the plan commit per partition.
    key = jax.random.PRNGKey(0)
    logX = 0.01 * jax.random.normal(key, (P, C), dtype=jnp.float32)

    def body(_, logX):
        X = jax.nn.softmax(logX, axis=1)
        load = w @ X  # [C]
        # Mirror step on d/dX sum_j load_j^2 = lag_p * 2 load_j, centered so
        # the step is invariant to uniform load shifts.
        grad = (load - jnp.mean(load)) / scale
        logX = logX - eta * (w / scale)[:, None] * grad[None, :]
        # Sinkhorn pair: scale columns toward the balanced count marginal,
        # rows back to stochastic (in log space for stability).
        X = jax.nn.softmax(logX, axis=1)
        colsum = jnp.sum(X, axis=0, where=valid[:, None]) + 1e-9
        logX = logX + jnp.log(cap / colsum)[None, :]
        logX = logX - jax.nn.logsumexp(logX, axis=1, keepdims=True)
        return logX

    logX = lax.fori_loop(0, iters, body, logX)
    return jax.nn.softmax(logX, axis=1)


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "refine_iters")
)
def assign_topic_sinkhorn(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    refine_iters: int = 24,
):
    """Integral, count-balanced assignment from the Sinkhorn plan.

    Rounding: partitions in descending-lag order pick the *least-loaded*
    open consumer (capacity floor/ceil(n/C)), with the transport plan as a
    continuous tie-break bonus — i.e. LPT steered by the OT relaxation.
    A pairwise-exchange refinement pass (:mod:`..ops.refine`) then tightens
    max/mean imbalance below what any single greedy pass reaches.

    Same output contract as the greedy kernels: (choice int32[P] in input
    order, counts int32[C], totals[C]).
    """
    from ..ops.refine import refine_assignment

    C = int(num_consumers)
    P = lags.shape[0]
    X = sinkhorn_plan(lags, valid, num_consumers=C, iters=iters)

    n_valid = jnp.sum(valid.astype(jnp.int32))
    floor_cap = n_valid // C
    extras = n_valid - floor_cap * C  # this many consumers may hit ceil

    neg_lag = jnp.where(valid, -lags, jnp.iinfo(lags.dtype).max)
    order = jnp.argsort(neg_lag)  # lag desc, padding last

    w = jnp.where(valid, lags, 0).astype(jnp.float32)
    scale = jnp.maximum(jnp.sum(w), 1.0) / C

    def step(carry, p):
        counts, totals, extras_left = carry
        is_valid = valid[p]
        # A consumer is open if under floor cap, or at floor cap while
        # ceil-slots remain.
        under_floor = counts < floor_cap
        at_floor = (counts == floor_cap) & (extras_left > 0)
        open_mask = under_floor | at_floor
        # Least load first; the plan contributes a sub-lag-unit bonus so it
        # decides ties without overriding the load ordering.
        score = totals.astype(jnp.float32) / scale - 0.01 * X[p]
        score = jnp.where(open_mask, score, jnp.inf)
        who = jnp.argmin(score).astype(jnp.int32)
        take = is_valid
        one_hot = (jnp.arange(C, dtype=jnp.int32) == who) & take
        used_extra = take & at_floor[who]
        counts = counts + one_hot.astype(jnp.int32)
        totals = totals + jnp.where(one_hot, lags[p], 0).astype(totals.dtype)
        extras_left = extras_left - used_extra.astype(jnp.int32)
        return (counts, totals, extras_left), jnp.where(take, who, -1)

    init = (
        jnp.zeros((C,), jnp.int32),
        jnp.zeros((C,), lags.dtype),
        extras,
    )
    (_, _, _), sorted_choice = lax.scan(step, init, order)
    choice = jnp.full((P,), -1, jnp.int32).at[order].set(sorted_choice)
    return refine_assignment(
        lags, valid, choice, num_consumers=C, iters=refine_iters
    )


def assign_sinkhorn(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    iters: int = 60,
) -> AssignmentMap:
    """Map-level Sinkhorn solve (same surface as
    :func:`..ops.dispatch.assign_device`); per-topic independence preserved."""
    from ..ops.dispatch import assign_per_topic, ensure_x64
    from ..ops.packing import pad_bucket

    ensure_x64()

    def solve_topic(lags, pids, num_consumers):
        P = lags.shape[0]
        P_pad = pad_bucket(P)
        lags_p = np.zeros(P_pad, dtype=np.int64)
        pids_p = np.zeros(P_pad, dtype=np.int32)
        valid = np.zeros(P_pad, dtype=bool)
        lags_p[:P], pids_p[:P], valid[:P] = lags, pids, True
        choice, _, _ = assign_topic_sinkhorn(
            lags_p, pids_p, valid, num_consumers=num_consumers, iters=iters
        )
        return choice

    return assign_per_topic(
        partition_lag_per_topic, subscriptions, solve_topic
    )
