"""Sinkhorn-style optimal-transport relaxation solver (implicit plan).

The greedy LPT core (reference semantics) is a 4/3-approximation for makespan
and is what the reference prescribes; this solver is the framework's
*quality* alternative (SURVEY §7 step 5; BASELINE config 4 compares the two
on heavy skew): it directly optimizes the north-star metric — max/mean lag
imbalance — while preserving the count-primary invariant
``max - min assigned partitions <= 1``.

Method: entropic mirror descent on the squared-load objective over the
transport polytope, with Sinkhorn-style alternating marginal scaling
(pattern references: the OT papers in PAPERS.md — FlashSinkhorn's
tile-streaming iteration, push-relabel additive approximation for rounding
intuition; patterns only, no code).

* relaxation variable  X in [0,1]^{P x C}, row-stochastic: X[p] is a
  distribution of partition p over consumers;
* objective  sum_j load_j^2  with  load_j = sum_p lag_p X[p,j]  — minimized
  exactly when loads are equal;
* update     X <- X * exp(-eta * ws_p * (load_j - mean load))  (mirror /
  multiplicative-weights step on the centered gradient, ws = lag/scale),
  followed by one Sinkhorn pair: column scaling toward the balanced count
  marginal P/C, then row re-normalization;
* rounding   partitions in descending-lag order pick the least-loaded open
  consumer (capacities floor/ceil(P/C)) with the plan as a continuous
  tie-break bonus — integral, count-balanced by construction — then a
  pairwise-exchange refinement pass (:mod:`..ops.refine`).

**TPU-native key idea — the plan is never materialized.**  Every update
above is rank-structured, so by induction the log-plan stays exactly

    logX[p, j] = noise(p, j) - ws_p * A_j + B_j   (+ row normalizer)

where ``A`` accumulates the mirror steps and ``B`` the column corrections —
the row normalizer cancels in the row softmax.  The iteration state is two
f32[C] vectors instead of a [P, C] matrix (524 MB at the 100k x 1k north
star), and each iteration needs only the plan's two marginal statistics,
computed by the fused tile-streaming kernel in :mod:`..ops.plan_stats`
(Pallas on TPU, tiled lax elsewhere) with O(P) HBM traffic.  The symmetry-
breaking noise is a deterministic integer hash, recomputable anywhere.
"""

from __future__ import annotations

import functools
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.plan_stats import (
    _pallas_available,
    implicit_plan_argmax,
    implicit_plan_rows,
    plan_stats,
)
from ..types import AssignmentMap, TopicPartitionLag

# Above this many partition rows the sequential rounding scan (one step per
# partition) dominates wall time, so the parallel argmax+repair rounding
# takes over (see _round_parallel).
_SCAN_ROUNDING_MAX_P = 32768


def sinkhorn_duals(
    lags: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    eta: float = 8.0,
):
    """Run the implicit-plan iteration; returns ``(A, B, ws)``.

    ``A``/``B`` are the f32[C] state vectors of the rank-structured
    log-plan; ``ws`` the f32[P] scaled lags (lag / ideal-per-consumer-load).
    Plan rows can be materialized on demand with
    :func:`..ops.plan_stats.implicit_plan_rows`.
    """
    # Resolve the Pallas-vs-lax choice EAGERLY: inside the trace below the
    # probe could not execute (a lowering failure would abort the compile
    # with no fallback, see plan_stats._pallas_available).
    _pallas_available()
    return _sinkhorn_duals_jit(
        lags, valid, num_consumers=num_consumers, iters=iters, eta=eta
    )


@functools.partial(jax.jit, static_argnames=("num_consumers", "iters"))
def _sinkhorn_duals_jit(
    lags: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    eta: float = 8.0,
):
    C = int(num_consumers)
    w = jnp.where(valid, lags, 0).astype(jnp.float32)
    total = jnp.maximum(jnp.sum(w), 1.0)
    scale = total / C  # ideal per-consumer load
    ws = w / scale
    maskf = valid.astype(jnp.float32)
    n_valid = jnp.maximum(jnp.sum(maskf), 1.0)
    cap = n_valid / C  # balanced count marginal

    eta32 = jnp.float32(eta)

    def body(_, AB):
        A, B = AB
        # Mirror step on d/dX sum_j load_j^2 ∝ ws_p * load_j, centered so
        # the step is invariant to uniform load shifts.  load is already in
        # ws units (= absolute load / scale).
        load, _ = plan_stats(ws, maskf, A, B)
        A = A + eta32 * (load - jnp.mean(load))
        # Sinkhorn pair: scale columns toward the balanced count marginal
        # (rows re-normalize implicitly in the softmax).
        _, colsum = plan_stats(ws, maskf, A, B)
        B = B + jnp.log(cap / (colsum + jnp.float32(1e-9)))
        return A, B

    A0 = jnp.zeros((C,), jnp.float32)
    B0 = jnp.zeros((C,), jnp.float32)
    A, B = lax.fori_loop(0, iters, body, (A0, B0))
    return A, B, ws


def _round_parallel(lags, ws, valid, A, B, C: int, floor_cap, extras):
    """Parallel (O(P log P), no per-partition scan) plan rounding.

    1. each partition takes its plan-argmax consumer (tiled, parallel);
    2. capacity repair: within each consumer's takers (sorted lag desc) the
       first cap_j keep their seat — the plan is near-balanced after the
       Sinkhorn iteration, so few overflow;
    3. the overflow re-seats positionally: the k-th largest-lag overflow
       partition takes the k-th open slot, slots ordered round-robin over
       consumers by ascending kept load (a one-shot round decomposition —
       each "round" hands every open consumer one partition, lightest
       first).  Count spread <= 1 holds by construction; the exchange
       refinement pass afterwards re-tightens lag balance.

    Returns choice int32[P] (input order, -1 for invalid rows).
    """
    P = ws.shape[0]
    cap = floor_cap + (jnp.arange(C, dtype=jnp.int32) < extras).astype(
        jnp.int32
    )  # int32[C], sums to n_valid

    jstar = implicit_plan_argmax(ws, valid, A, B)  # C sentinel for invalid

    # Group rows by (consumer, lag desc); sentinel group sorts last.
    neg_lag = jnp.where(valid, -lags, jnp.iinfo(lags.dtype).max)
    idx = jnp.arange(P, dtype=jnp.int32)
    _, _, perm = lax.sort((jstar, neg_lag, idx), num_keys=2)
    sj = jstar[perm]
    pos = idx - jnp.searchsorted(sj, jnp.arange(C + 1, dtype=jnp.int32))[
        jnp.clip(sj, 0, C)
    ].astype(jnp.int32)
    keep = (sj < C) & (pos < cap[jnp.clip(sj, 0, C - 1)])

    ws_s = ws[perm]
    sj_safe = jnp.clip(sj, 0, C - 1)
    kept_load = jnp.zeros((C,), jnp.float32).at[sj_safe].add(
        jnp.where(keep, ws_s, 0.0)
    )
    kept_cnt = jnp.zeros((C,), jnp.int32).at[sj_safe].add(
        keep.astype(jnp.int32)
    )
    rem = cap - kept_cnt  # open seats per consumer, >= 0

    # Open slots in (round, load-rank) order: slot (j, r) exists iff
    # r < rem_j; lighter consumers seat first within a round.
    load_rank = jnp.zeros((C,), jnp.int32).at[
        jnp.argsort(kept_load).astype(jnp.int32)
    ].set(jnp.arange(C, dtype=jnp.int32))
    cap_max = P // C + 1
    slot_r = jnp.repeat(
        jnp.arange(cap_max, dtype=jnp.int32)[:, None], C, axis=1
    ).reshape(-1)
    slot_j = jnp.repeat(
        jnp.arange(C, dtype=jnp.int32)[None, :], cap_max, axis=0
    ).reshape(-1)
    slot_open = slot_r < rem[slot_j]
    slot_key = jnp.where(
        slot_open,
        slot_r * jnp.int32(C) + load_rank[slot_j],
        jnp.iinfo(jnp.int32).max,
    )
    _, slot_j_sorted = lax.sort((slot_key, slot_j), num_keys=1)

    # Overflow rows in lag-desc order meet slots positionally.
    overflow = valid[perm] & ~keep
    okey = jnp.where(overflow, neg_lag[perm], jnp.iinfo(lags.dtype).max)
    _, oorder = lax.sort((okey, idx), num_keys=1)
    n_over = jnp.sum(overflow.astype(jnp.int32))
    seat = jnp.where(
        idx < n_over, slot_j_sorted[jnp.minimum(idx, C * cap_max - 1)], -1
    )
    choice_sorted = jnp.where(keep, sj, -1)
    choice_sorted = choice_sorted.at[oorder].max(seat)

    return jnp.full((P,), -1, jnp.int32).at[perm].set(choice_sorted)


def assign_topic_sinkhorn(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    refine_iters: int = 24,
):
    """Integral, count-balanced assignment from the implicit Sinkhorn plan.

    Rounding: partitions in descending-lag order pick the *least-loaded*
    open consumer (capacity floor/ceil(n/C)), with the plan row —
    materialized per step from the implicit state — as a continuous
    tie-break bonus, i.e. LPT steered by the OT relaxation.  A pairwise-
    exchange refinement pass (:mod:`..ops.refine`) then tightens max/mean
    imbalance below what any single greedy pass reaches.

    Same output contract as the greedy kernels: (choice int32[P] in input
    order, counts int32[C], totals[C]).
    """
    _pallas_available()  # resolve kernel choice eagerly, outside the trace
    return _assign_topic_sinkhorn_jit(
        lags, partition_ids, valid, num_consumers=num_consumers,
        iters=iters, refine_iters=refine_iters,
    )


@functools.partial(
    jax.jit, static_argnames=("num_consumers", "iters", "refine_iters")
)
def _assign_topic_sinkhorn_jit(
    lags: jax.Array,
    partition_ids: jax.Array,
    valid: jax.Array,
    num_consumers: int,
    iters: int = 60,
    refine_iters: int = 24,
):
    from ..ops.refine import refine_assignment

    C = int(num_consumers)
    P = lags.shape[0]
    A, B, ws = _sinkhorn_duals_jit(lags, valid, num_consumers=C, iters=iters)

    n_valid = jnp.sum(valid.astype(jnp.int32))
    floor_cap = n_valid // C
    extras = n_valid - floor_cap * C  # this many consumers may hit ceil

    if P > _SCAN_ROUNDING_MAX_P:
        # Large topics: the per-partition scan below would dominate wall
        # time; round in parallel and lean on the refinement pass.  The
        # one-shot rounding starts coarser than the sequential scan, so
        # floor the refinement budget (each round retires up to C//2
        # disjoint exchanges — at these shapes 96 rounds is ~ms and takes
        # max/mean to within a fraction of a percent of the bound).
        choice = _round_parallel(
            lags, ws, valid, A, B, C, floor_cap, extras
        )
        return refine_assignment(
            lags, valid, choice, num_consumers=C,
            iters=max(refine_iters, 96),
        )

    neg_lag = jnp.where(valid, -lags, jnp.iinfo(lags.dtype).max)
    order = jnp.argsort(neg_lag).astype(jnp.int32)  # lag desc, padding last

    def step(carry, p):
        counts, totals, extras_left = carry
        is_valid = valid[p]
        # A consumer is open if under floor cap, or at floor cap while
        # ceil-slots remain.
        under_floor = counts < floor_cap
        at_floor = (counts == floor_cap) & (extras_left > 0)
        open_mask = under_floor | at_floor
        # Least (scaled) load first; the plan row contributes a sub-unit
        # bonus so it decides ties without overriding the load ordering.
        xrow = implicit_plan_rows(p[None], ws[p][None], A, B)[0]
        score = totals - jnp.float32(0.01) * xrow
        score = jnp.where(open_mask, score, jnp.inf)
        who = jnp.argmin(score).astype(jnp.int32)
        take = is_valid
        one_hot = (jnp.arange(C, dtype=jnp.int32) == who) & take
        used_extra = take & at_floor[who]
        counts = counts + one_hot.astype(jnp.int32)
        totals = totals + jnp.where(one_hot, ws[p], 0.0)
        extras_left = extras_left - used_extra.astype(jnp.int32)
        return (counts, totals, extras_left), jnp.where(take, who, -1)

    init = (
        jnp.zeros((C,), jnp.int32),
        jnp.zeros((C,), jnp.float32),
        extras,
    )
    (_, _, _), sorted_choice = lax.scan(step, init, order)
    choice = jnp.full((P,), -1, jnp.int32).at[order].set(sorted_choice)
    return refine_assignment(
        lags, valid, choice, num_consumers=C, iters=refine_iters
    )


def assign_sinkhorn(
    partition_lag_per_topic: Mapping[str, Sequence[TopicPartitionLag]],
    subscriptions: Mapping[str, Sequence[str]],
    iters: int = 60,
    refine_iters: int = 24,
) -> AssignmentMap:
    """Map-level Sinkhorn solve (same surface as
    :func:`..ops.dispatch.assign_device`); per-topic independence preserved.

    ``iters``/``refine_iters`` are the quality-vs-latency knobs, exposed
    through the config layer as ``tpu.assignor.sinkhorn.iters`` /
    ``tpu.assignor.refine.iters``."""
    from ..ops.dispatch import assign_per_topic, ensure_x64
    from ..ops.packing import pad_topic_rows

    ensure_x64()

    def solve_topic(lags, pids, num_consumers):
        lags_p, pids_p, valid = pad_topic_rows(lags, pids)
        choice, _, _ = assign_topic_sinkhorn(
            lags_p, pids_p, valid, num_consumers=num_consumers,
            iters=iters, refine_iters=refine_iters,
        )
        return choice

    return assign_per_topic(
        partition_lag_per_topic, subscriptions, solve_topic
    )
