"""Solver families: the exact greedy oracle and relaxation-based solvers."""

from .greedy import assign_greedy, assign_topic_greedy, consumers_per_topic

__all__ = ["assign_greedy", "assign_topic_greedy", "consumers_per_topic"]
