"""Closed-loop overload control: SLO classes, shed ladder, elasticity.

The sidecar serves many tenants through one device pipeline, and under
overload every stream used to degrade equally — the only pressure valve
was the per-request deadline budget, which fires *after* a request has
already parked behind a full wave.  This module is the control plane in
front of that: per-tenant **SLO classes**, a registry-fed **overload
detector** that walks a shed ladder, and the **elasticity** math behind
the wire ``{"method": "recommend"}`` call (the consumer-count
recommendation loop of the multi-objective consumer-group autoscaling
literature, arXiv:2402.06085) — degrade batch efficiency before
latency, and shed the lowest class first.

SLO classes
-----------

Every stream carries one of three classes (config
``tpu.assignor.slo.class.<stream>``, overridable per request via the
wire ``params.slo_class``):

================  ====  ======  =============================================
class             rank  weight  meaning
================  ====  ======  =============================================
``critical``        0       4   never shed; placed first in every wave
``standard``        1       2   default; degraded only at the last rung
``best_effort``     2       1   first to degrade, then first to be rejected
================  ====  ======  =============================================

Rank orders megabatch chunk placement (ops/coalesce sorts every flush
by ``(rank, remaining deadline)``, so a critical stream never parks
behind a full best-effort wave); weight scales a class's contribution
to the queue-depth pressure signal.  A per-class **deadline budget**
(config ``tpu.assignor.slo.deadline.ms.<class>``) caps the request's
deadline budget below the global ``solve.timeout.ms``, and rides into
the coalescer as the submission's absolute deadline — a row whose
remaining budget cannot survive a full flush is re-routed to the
inline path (or shed) instead of poisoning the wave.

The shed ladder
---------------

:class:`OverloadController` derives a pressure score from three
registry-fed signals — an EWMA of the in-flight stream-request depth,
the windowed p99 of ``klba_span_duration_ms{span=stream.epoch}``
(bucket-delta since the previous evaluation, so one cold compile does
not poison the signal forever), and the stream breaker's state — and
maps it onto the rungs:

====  ====================  =================================================
rung  name                  action
====  ====================  =================================================
0     ``none``              admit everything, full admission window
1     ``shrink_window``     coalescer admission window scaled down
2     ``degrade_best_effort``  best_effort served ``kept_previous`` (zero
                            device work; warm state intact)
3     ``reject_best_effort``  best_effort rejected with a retry-after hint
4     ``degrade_standard``  standard also ``kept_previous``; critical still
                            solves
====  ====================  =================================================

Escalation is immediate; de-escalation steps down one rung per
``cooldown_s`` below threshold (hysteresis — a stampede must not
flap the ladder).  Every shed emits a flight record and
``klba_shed_total{class,rung}``; rung transitions set the
``klba_overload_rung`` gauge and record an ``overload_rung`` flight
record.  The fault point ``shed.decide`` fires inside
:meth:`OverloadController.admission` — the service FAILS OPEN (admits)
when the decision path itself faults, pinned by the chaos suite.

Elasticity
----------

:func:`recommend_consumers` projects a stream's backlog ``horizon_s``
ahead from its recent (time, total lag) samples and sizes the group so
the projected backlog per consumer stays at today's level::

    rec = ceil(C * (lag_now + max(0, slope) * horizon) / lag_now)

Monotone in the lag trend by construction (the acceptance gate the
bench's stampede probe pins); the current overload rung bumps the
floor to ``C + 1`` once the ladder is degrading traffic.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

from . import faults, metrics
from . import trace as trace_mod

LOGGER = logging.getLogger(__name__)

#: The SLO classes, most- to least-important.  Index = rank (placement
#: and shed order both key on it).
SLO_CLASSES = ("critical", "standard", "best_effort")

_CLASS_RANK = {c: i for i, c in enumerate(SLO_CLASSES)}

#: Default admission weights (config-overridable is deliberately NOT
#: offered — the weights only scale the depth-pressure signal, and a
#: per-deployment knob there would be unfalsifiable tuning surface).
CLASS_WEIGHTS = {"critical": 4.0, "standard": 2.0, "best_effort": 1.0}

#: Shed-ladder rungs, least to most severe (index = rung).
RUNGS = (
    "none",
    "shrink_window",
    "degrade_best_effort",
    "reject_best_effort",
    "degrade_standard",
)

#: Coalescer admission-window scale per rung — the STANDARD class's
#: table (back-compat anchor: the unlabeled ``window_scale`` fields
#: and the legacy single-scale coalescer hook read this one): rung 1
#: is "shrink the admission window" (smaller waves, lower parked
#: latency); deeper rungs keep shrinking — batch efficiency yields
#: before latency does.
_WINDOW_SCALE = (1.0, 0.5, 0.25, 0.25, 0.1)

#: Per-CLASS window tables (ROADMAP overload (b)), indexed by class
#: rank then rung: rung 1's shrink lands class-by-class — the critical
#: window stays WIDE (a critical epoch keeps its full coalescing
#: opportunity; its latency is protected by placement order and the
#: deadline triage, not by starving its batches) while best_effort
#: shrinks hardest (it is the traffic the ladder is about to degrade
#: anyway, so its waves go small first).
_WINDOW_SCALE_BY_RANK = (
    (1.0, 1.0, 0.5, 0.5, 0.25),   # critical
    _WINDOW_SCALE,                # standard
    (1.0, 0.25, 0.1, 0.1, 0.05),  # best_effort
)

#: Pressure thresholds: rung i engages at pressure >= _THRESHOLDS[i-1].
_THRESHOLDS = (1.0, 1.5, 2.5, 4.0)


def class_rank(klass: str) -> int:
    return _CLASS_RANK[klass]


def _held_window_scale(rung: int, standing: float, rank: int = 1) -> float:
    """THE takeover window-hold rule, in one place (admission decisions
    AND the operator snapshot read it): while any standing takeover
    pressure is parked, the admission window is held at rung-1 scale
    even at rung 0 — per CLASS, so the hold also leaves the critical
    window wide."""
    table = _WINDOW_SCALE_BY_RANK[rank]
    scale = table[rung]
    if standing > 0:
        return min(scale, table[1])
    return scale


def _held_window_scales(rung: int, standing: float) -> Tuple[float, ...]:
    """All three classes' held window scales, rank order."""
    return tuple(
        _held_window_scale(rung, standing, rank)
        for rank in range(len(SLO_CLASSES))
    )


#: Get-or-create cache for the shed counters (sheds happen on the
#: overloaded hot path, where a label-dict registry lookup per event is
#: the wrong cost).  Plain dict: get/set are GIL-atomic, and a racing
#: double-create just fetches the same registry child twice.
_SHED_COUNTERS: Dict[Tuple[str, str], "metrics.Counter"] = {}


def record_shed(
    klass: str,
    rung_name: str,
    served: Optional[str],
    stream_id: Optional[str] = None,
    request_id: Optional[str] = None,
    scope: Optional[Any] = None,
) -> None:
    """Account one shed event — ``klba_shed_total{class,rung}`` plus a
    flight record and a ``shed`` anomaly mark on the indicted trace
    (tail sampling ALWAYS keeps shed traces) — with ONE schema no
    matter which layer shed the request (the controller's ladder or
    the coalescer's deadline triage).  ``served`` is what the client
    got (``kept_previous`` / ``rejected``), or None when the shedding
    layer cannot know (the coalescer sheds before the submitter's
    recovery picks the answer).  ``request_id``/``scope`` are only
    needed from threads outside the request scope — the coalescer
    flusher shedding a parked submitter's row passes the submitter's
    captured scope token so the mark lands on THAT trace."""
    key = (klass, rung_name)
    counter = _SHED_COUNTERS.get(key)
    if counter is None:
        counter = _SHED_COUNTERS[key] = metrics.REGISTRY.counter(
            "klba_shed_total", {"class": klass, "rung": rung_name}
        )
    counter.inc()
    if scope is not None:
        trace_mod.mark_state(getattr(scope, "trace", None), "shed")
    else:
        trace_mod.mark("shed")
    rec: Dict[str, Any] = {
        "class": klass,
        "rung": rung_name,
        "served": served,
        "stream_id": stream_id,
    }
    if request_id is not None:
        rec["request_id"] = request_id
    if scope is not None and getattr(scope, "trace", None) is not None:
        rec.setdefault("trace_id", scope.trace.trace_id)
    metrics.FLIGHT.record("shed", rec)


class ShedReject(RuntimeError):
    """A request rejected by the shed ladder (never an internal error):
    the wire layer turns this into an error envelope carrying the class,
    the rung, and a ``retry_after_ms`` hint for the client's backoff."""

    def __init__(self, klass: str, rung: str, retry_after_ms: int):
        super().__init__(
            f"overload: {klass!r} traffic is being shed at rung {rung!r}; "
            f"retry after {retry_after_ms} ms"
        )
        self.klass = klass
        self.rung = rung
        self.retry_after_ms = retry_after_ms
        # Stamped by the service CLIENT when it rebuilds the rejection
        # from an error envelope: the shedding sidecar's trace id.
        self.trace_id: Optional[str] = None


class SloPolicy:
    """Per-stream class resolution + per-class deadline budgets.

    ``classes`` maps stream id -> class name (from
    ``tpu.assignor.slo.class.<stream>``); a wire-level override wins.
    ``deadline_s`` maps class name -> seconds; :meth:`budget_s` returns
    the TIGHTER of the class deadline and the service's global solve
    timeout (a class budget can only shrink the request budget, never
    extend past the watchdog's)."""

    def __init__(
        self,
        classes: Optional[Mapping[str, str]] = None,
        deadline_s: Optional[Mapping[str, float]] = None,
        default_class: str = "standard",
    ):
        self._classes = dict(classes or {})
        self._deadline_s = dict(deadline_s or {})
        for sid, klass in self._classes.items():
            if klass not in SLO_CLASSES:
                raise ValueError(
                    f"unknown SLO class {klass!r} for stream {sid!r}; "
                    f"valid: {list(SLO_CLASSES)}"
                )
        for klass, secs in self._deadline_s.items():
            if klass not in SLO_CLASSES:
                raise ValueError(
                    f"unknown SLO class {klass!r} in deadline map; "
                    f"valid: {list(SLO_CLASSES)}"
                )
            if not secs > 0:
                raise ValueError(
                    f"SLO deadline for {klass!r} must be > 0, got {secs}"
                )
        if default_class not in SLO_CLASSES:
            raise ValueError(f"unknown default class {default_class!r}")
        self.default_class = default_class

    def resolve(self, stream_id: Any, override: Any = None) -> str:
        """The stream's effective class: wire override > config map >
        default.  An unknown override is a client error (loud, like
        every other wire-boundary validation)."""
        if override is not None:
            if override not in SLO_CLASSES:
                raise ValueError(
                    f"unknown slo_class {override!r}; valid: "
                    f"{list(SLO_CLASSES)}"
                )
            return override
        if isinstance(stream_id, str):
            return self._classes.get(stream_id, self.default_class)
        return self.default_class

    def deadline_s(self, klass: str) -> Optional[float]:
        return self._deadline_s.get(klass)

    def budget_s(
        self, klass: str, global_timeout_s: Optional[float]
    ) -> Optional[float]:
        """The request's total deadline budget for this class."""
        d = self._deadline_s.get(klass)
        if d is None:
            return global_timeout_s
        if global_timeout_s is None:
            return d
        return min(d, global_timeout_s)


class _Decision:
    """One admission decision: what to do with this request, and the
    ladder context that produced it (snapshotted — the rung may move
    while the request runs)."""

    __slots__ = ("action", "rung", "rung_name", "retry_after_ms",
                 "window_scale", "window_scales")

    def __init__(self, action: str, rung: int, retry_after_ms: int):
        self.action = action  # "admit" | "degrade" | "reject"
        self.rung = rung
        self.rung_name = RUNGS[rung]
        self.retry_after_ms = retry_after_ms
        # window_scale stays the STANDARD class's scale (back-compat
        # reads); window_scales is the per-class (rank-ordered) triple
        # the coalescer actually applies (ROADMAP overload (b)).
        self.window_scale = _WINDOW_SCALE[rung]
        self.window_scales = tuple(
            t[rung] for t in _WINDOW_SCALE_BY_RANK
        )


class OverloadController:
    """The service-level overload detector + shed ladder (module
    docstring).  One instance per service; thread-safe; clock
    injectable (L012 discipline) so the hysteresis is testable without
    real waits.

    ``latency_budget_ms`` is the epoch-latency level treated as
    pressure 1.0 (default: half the solve timeout — permissive, so an
    unconfigured sidecar never sheds on the cold-compile epochs);
    ``depth_high`` is the weighted in-flight depth treated as pressure
    1.0.  ``eval_interval_s`` rate-limits the registry walk; between
    evaluations the cached rung serves."""

    def __init__(
        self,
        latency_budget_ms: float = 60_000.0,
        depth_high: float = 24.0,
        ewma_alpha: float = 0.3,
        cooldown_s: float = 1.0,
        eval_interval_s: float = 0.1,
        clock: Optional[Callable[[], float]] = None,
        breaker_open: Optional[Callable[[], bool]] = None,
    ):
        if not latency_budget_ms > 0:
            raise ValueError(
                f"latency_budget_ms={latency_budget_ms} must be > 0"
            )
        if not depth_high > 0:
            raise ValueError(f"depth_high={depth_high} must be > 0")
        self.latency_budget_ms = float(latency_budget_ms)
        self.depth_high = float(depth_high)
        self.ewma_alpha = float(ewma_alpha)
        self.cooldown_s = float(cooldown_s)
        self.eval_interval_s = float(eval_interval_s)
        self._clock = clock or metrics.REGISTRY.clock
        self._breaker_open = breaker_open or (lambda: False)
        self._lock = threading.Lock()
        self._ewma_depth = 0.0
        # Standing pressure (ROADMAP lifecycle (e) — lease-aware
        # shedding during the takeover window): a constant term the
        # sidecar parks here for adopted-but-still-cold streams after
        # a takeover/restart.  Unlike the depth EWMA it does NOT decay
        # — it is released stream by stream as each recovered stream
        # serves its first (warming) epoch — and while any of it is
        # outstanding the admission window is held at rung-1 scale, so
        # a replacement serving cold streams cannot stampede itself.
        self._standing = 0.0
        self._rung = 0
        self._pressure = 0.0
        self._p99_ms: Optional[float] = None
        self._last_eval: Optional[float] = None
        self._last_step_down: float = self._clock()
        # Windowed latency signal: bucket-delta p99 of the stream.epoch
        # span since the previous evaluation (one cold compile must not
        # poison the lifetime percentile forever).
        self._epoch_hist = metrics.REGISTRY.histogram(
            "klba_span_duration_ms", {"span": "stream.epoch"}
        )
        self._hist_prev = self._epoch_hist.state()
        self._m_rung = metrics.REGISTRY.gauge("klba_overload_rung")
        self._m_pressure = metrics.REGISTRY.gauge("klba_overload_pressure")

    # -- signals -----------------------------------------------------------

    def note_depth(self, weighted_depth: float) -> None:
        """Feed the weighted in-flight depth (sum of CLASS_WEIGHTS over
        requests currently in the stream path)."""
        with self._lock:
            self._ewma_depth += self.ewma_alpha * (
                float(weighted_depth) - self._ewma_depth
            )

    def seed_recovery_depth(self, weighted_depth: float) -> None:
        """Recovery-aware ladder seed (ROADMAP lifecycle (c)): a
        restarting sidecar knows every recovered stream will fire its
        next epoch at once — seed the depth EWMA with that stampede's
        weighted depth (never DOWNWARD: a restored snapshot may carry
        a higher live reading) and force the next admission decision
        to re-evaluate, so a restart under a live stampede
        re-escalates on the FIRST post-boot decision instead of
        waiting one evaluation interval.  If the stampede never
        materializes the EWMA decays through the normal hysteresis."""
        with self._lock:
            self._ewma_depth = max(
                self._ewma_depth, float(weighted_depth)
            )
            self._last_eval = None

    def add_standing_pressure(self, weight: float) -> None:
        """Park ``weight`` (a CLASS_WEIGHTS sum) as standing takeover
        pressure and force the next admission decision to re-evaluate
        (see the ``_standing`` comment)."""
        if weight <= 0:
            return
        with self._lock:
            self._standing += float(weight)
            self._last_eval = None

    def release_standing_pressure(self, weight: float) -> None:
        """Release ``weight`` of the parked takeover pressure (one
        adopted stream finished warming — its first epoch served, it
        was reset, or it was discarded).  Clamped at zero and forces a
        re-evaluation, so the ladder can step down through the normal
        hysteresis the moment the warm-up drains."""
        if weight <= 0:
            return
        with self._lock:
            self._standing = max(0.0, self._standing - float(weight))
            self._last_eval = None

    def standing_pressure(self) -> float:
        with self._lock:
            return self._standing

    def _windowed_p99(self) -> Optional[float]:
        """p99 of the stream.epoch observations made since the previous
        evaluation (bucket-wise delta) — None when nothing new."""
        cur = self._epoch_hist.state()
        prev, self._hist_prev = self._hist_prev, cur
        count = cur["count"] - prev["count"]
        if count <= 0:
            return None
        deltas = [a - b for a, b in zip(cur["buckets"], prev["buckets"])]
        return metrics._delta_percentile(deltas, count, 0.99)

    def _evaluate_locked(self, now: float) -> None:
        """Caller holds the lock: recompute pressure + rung (rate
        limited to ``eval_interval_s``)."""
        if (
            self._last_eval is not None
            and now - self._last_eval < self.eval_interval_s
        ):
            return
        self._last_eval = now
        p99 = self._windowed_p99()
        if p99 is not None:
            self._p99_ms = p99
        elif self._p99_ms is not None:
            # No stream.epoch completed since the last evaluation: the
            # congestion that p99 measured has drained (or the ladder
            # is rejecting everything that would refresh it) — decay
            # the stale signal so an all-shed class mix cannot pin the
            # ladder at its last reading forever (livelock: rejected
            # requests never produce new epochs).
            self._p99_ms *= 0.8
            if self._p99_ms < 1.0:
                self._p99_ms = None
        # Standing takeover pressure is a FLOOR under the depth signal,
        # not an addend: seed_recovery_depth already parks the same
        # recovered weight in the EWMA, and summing the two would read
        # every restart one rung harsher than the round-11 recovery
        # seeding was designed for.  max() keeps the ladder where the
        # seed put it while the EWMA decays, and hands over to live
        # traffic smoothly as adopted streams warm.
        depth_pressure = (
            max(self._ewma_depth, self._standing) / self.depth_high
        )
        lat_pressure = (
            (self._p99_ms / self.latency_budget_ms)
            if self._p99_ms is not None else 0.0
        )
        pressure = max(depth_pressure, lat_pressure)
        if self._breaker_open():
            pressure += 1.0
        self._pressure = pressure
        target = 0
        for i, threshold in enumerate(_THRESHOLDS):
            if pressure >= threshold:
                target = i + 1
        if target > self._rung:
            # Escalation is immediate — the ladder's whole point is to
            # act before queues melt.
            self._transition(target, now)
        elif target < self._rung:
            # De-escalate one rung per cooldown below threshold.
            if now - self._last_step_down >= self.cooldown_s:
                self._transition(self._rung - 1, now)
        self._m_pressure.set(pressure)

    def _transition(self, rung: int, now: float) -> None:
        old = self._rung
        self._rung = rung
        self._last_step_down = now
        self._m_rung.set(rung)
        metrics.FLIGHT.record(
            "overload_rung",
            {
                "from": RUNGS[old],
                "to": RUNGS[rung],
                "pressure": round(self._pressure, 3),
                "ewma_depth": round(self._ewma_depth, 3),
                "p99_ms": self._p99_ms,
            },
        )
        LOGGER.warning(
            "overload ladder %s -> %s (pressure %.2f, depth %.2f, "
            "p99 %s ms)",
            RUNGS[old], RUNGS[rung], self._pressure, self._ewma_depth,
            self._p99_ms,
        )

    # -- decisions ---------------------------------------------------------

    def admission(self, klass: str) -> _Decision:
        """Decide this request's fate under the current ladder rung.

        Fault point ``shed.decide`` fires here: the SERVICE fails open
        (admits) when the decision path faults — overload control must
        never be the thing that takes healthy traffic down."""
        faults.fire("shed.decide")
        now = self._clock()
        with self._lock:
            self._evaluate_locked(now)
            rung = self._rung
            pressure = self._pressure
            standing = self._standing
        rank = _CLASS_RANK[klass]
        action = "admit"
        if rung >= 4 and rank >= 1:
            action = "reject" if rank >= 2 else "degrade"
        elif rung >= 3 and rank >= 2:
            action = "reject"
        elif rung >= 2 and rank >= 2:
            action = "degrade"
        retry_ms = int(min(5000.0, max(100.0, self.cooldown_s * 1000.0
                                       * max(pressure, 1.0))))
        decision = _Decision(action, rung, retry_ms)
        # Takeover window (ROADMAP lifecycle (e)): while adopted
        # streams are still warming, hold the megabatch admission
        # window at rung-1 scale even at rung 0 — smaller waves until
        # the replacement's cold streams have all served once, so the
        # post-takeover stampede trickles instead of parking whole
        # fleets behind one giant cold wave.  Applied per class: the
        # critical table's rung-1 scale is 1.0, so critical waves stay
        # full-width through both the hold and rung 1.
        decision.window_scale = _held_window_scale(rung, standing)
        decision.window_scales = _held_window_scales(rung, standing)
        return decision

    def note_shed(
        self, klass: str, rung_name: str, served: str,
        stream_id: Optional[str] = None,
    ) -> None:
        """Account one shed event: ``klba_shed_total{class,rung}`` plus
        a flight record (every shed is visible post-incident) — thin
        delegate to the module's :func:`record_shed`, the ONE schema
        every shedding layer shares."""
        record_shed(klass, rung_name, served, stream_id=stream_id)

    def rung(self) -> int:
        with self._lock:
            return self._rung

    # -- lifecycle snapshot (utils/snapshot) -------------------------------

    def export_state(self) -> Dict[str, Any]:
        """Host-durable ladder state for the lifecycle snapshot: the
        rung plus the pressure signals that produced it.  Restoring the
        rung is what keeps a restart from serving the post-deploy
        stampede at rung 0 with a zeroed detector — the ladder resumes
        where it was and de-escalates through the normal hysteresis."""
        with self._lock:
            return {
                "rung": self._rung,
                "pressure": self._pressure,
                "ewma_depth": self._ewma_depth,
                "p99_ms": self._p99_ms,
            }

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Adopt exported ladder state after a restart (clamped to the
        known rungs; malformed input is discarded whole — overload
        control fails open, same contract as the admission path).  The
        step-down clock restarts now, so de-escalation still waits a
        full ``cooldown_s`` before the first downward step."""
        try:
            rung = min(max(int(state.get("rung", 0)), 0), len(RUNGS) - 1)
            pressure = float(state.get("pressure", 0.0))
            ewma = float(state.get("ewma_depth", 0.0))
            p99 = state.get("p99_ms")
            p99_ms = float(p99) if p99 is not None else None
        except (TypeError, ValueError, AttributeError):
            LOGGER.warning(
                "discarding malformed overload snapshot", exc_info=True
            )
            return
        with self._lock:
            self._rung = rung
            self._pressure = pressure
            self._ewma_depth = ewma
            self._p99_ms = p99_ms
            self._last_step_down = self._clock()
            self._m_rung.set(rung)
            self._m_pressure.set(pressure)

    def snapshot(self) -> Dict[str, Any]:
        """The operator's view (wire ``stats`` / ``recommend``)."""
        with self._lock:
            return {
                "rung": RUNGS[self._rung],
                "rung_index": self._rung,
                "pressure": round(self._pressure, 4),
                "ewma_depth": round(self._ewma_depth, 4),
                "standing_pressure": round(self._standing, 4),
                "p99_ms": self._p99_ms,
                "window_scale": _held_window_scale(
                    self._rung, self._standing
                ),
                "window_scales": {
                    klass: _held_window_scale(
                        self._rung, self._standing, rank
                    )
                    for rank, klass in enumerate(SLO_CLASSES)
                },
                "latency_budget_ms": self.latency_budget_ms,
                "depth_high": self.depth_high,
            }


def recommend_consumers(
    samples: Sequence[Tuple[float, float]],
    consumers: int,
    partitions: int,
    horizon_s: float = 60.0,
) -> Tuple[int, float]:
    """Consumer-count recommendation from (time_s, total_lag) samples.

    Projects the backlog ``horizon_s`` ahead at the window's trend and
    sizes the group so per-consumer backlog stays at today's level:
    ``ceil(C * projected / now)``.  Monotone non-decreasing in the lag
    slope (the bench gate); clamped to ``[1, partitions]`` — more
    consumers than partitions can never help (Kafka semantics).  Fewer
    than two samples (or a zero-length window) recommend the status
    quo.  Returns ``(recommended_consumers, slope_lag_per_s)``."""
    consumers = max(int(consumers), 1)
    floor_parts = max(int(partitions), 1)
    if len(samples) < 2:
        return min(consumers, floor_parts), 0.0
    t0, l0 = samples[0]
    t1, l1 = samples[-1]
    dt = t1 - t0
    if dt <= 0:
        return min(consumers, floor_parts), 0.0
    slope = (float(l1) - float(l0)) / dt
    lag_now = max(float(l1), 1.0)
    growth = max(0.0, slope) * horizon_s / lag_now
    rec = math.ceil(consumers * (1.0 + growth))
    return min(max(rec, 1), floor_parts), slope


def recommend_payload(
    streams: Mapping[str, Dict[str, Any]],
    overload: Dict[str, Any],
    horizon_s: float = 60.0,
) -> Dict[str, Any]:
    """Assemble the wire ``recommend`` result: per-stream entries (each
    holding ``samples`` [(t, lag), ...] oldest-first, ``consumers``,
    ``partitions``, ``slo_class``) plus the overload snapshot.  Once
    the ladder is actively degrading (rung >= 2) every stream's floor
    is ``C + 1`` — the detector is saying capacity, not drift."""
    degrading = overload.get("rung_index", 0) >= 2
    out: Dict[str, Any] = {"overload": overload, "streams": {}}
    for sid, info in streams.items():
        C = int(info["consumers"])
        P = int(info["partitions"])
        rec, slope = recommend_consumers(
            info["samples"], C, P, horizon_s=horizon_s
        )
        if degrading:
            rec = min(max(rec, C + 1), max(P, 1))
        out["streams"][sid] = {
            "slo_class": info["slo_class"],
            "consumers": C,
            "partitions": P,
            "recommended_consumers": rec,
            "lag_trend_per_s": round(slope, 3),
            "total_lag": int(info["samples"][-1][1])
            if info["samples"] else 0,
            "samples": len(info["samples"]),
            "horizon_s": horizon_s,
        }
    return out
