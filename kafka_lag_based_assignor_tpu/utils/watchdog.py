"""Timeout-based failure detection for accelerator calls.

The reference's failure model is exception propagation (broker RPCs abort
the rebalance, SURVEY §2.4.9) — but an accelerator behind a
tunnel/sidecar can also *hang* (observed in practice: a wedged transport
makes even device enumeration block forever).  A consumer-group rebalance
must never block on the accelerator past its rebalance timeout, so device
solves run under a watchdog: the call executes in a daemon worker thread
and, on timeout, the caller falls back to the host path while the stuck
call is abandoned (threads blocked in a wedged RPC cannot be force-killed
from Python; abandoning is the correct containment — the daemon thread dies
with the process and later calls go straight to the fallback).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, TypeVar

LOGGER = logging.getLogger(__name__)

T = TypeVar("T")


class SolveTimeout(Exception):
    """Raised when a watched call exceeds its deadline."""


class Watchdog:
    """Runs callables with a deadline on abandonable daemon threads.

    Deliberately NOT a ThreadPoolExecutor: the executor's atexit hook JOINS
    its workers, so a process that abandoned a hung solve would block at
    shutdown for the full hang.  A bare daemon thread dies with the process.

    A timeout marks the watchdog *tripped* so subsequent solves skip the
    accelerator immediately (fast host fallback) instead of queueing fresh
    threads behind a wedged transport.  The trip is NOT permanent: after
    ``cooldown_s`` the next solve probes the accelerator again, so one
    transient stall (e.g. a slow first-rebalance XLA compile) cannot
    banish a healthy device forever.  ``reset()`` clears the trip
    immediately (operator action).
    """

    def __init__(self, timeout_s: Optional[float], cooldown_s: float = 300.0):
        self.timeout_s = timeout_s
        self.cooldown_s = cooldown_s
        self._tripped_at: Optional[float] = None
        self._lock = threading.Lock()

    @property
    def tripped(self) -> bool:
        with self._lock:
            return self._tripped_at is not None and (
                time.monotonic() - self._tripped_at < self.cooldown_s
            )

    def reset(self) -> None:
        """Allow the accelerator another chance (e.g. operator action)."""
        with self._lock:
            self._tripped_at = None

    def call(self, fn: Callable[..., T], *args: Any, **kwargs: Any) -> T:
        """Run ``fn`` under the deadline.

        Raises SolveTimeout if the deadline passes or the watchdog tripped
        within the cooldown window.  With ``timeout_s`` None the call runs
        inline (watchdog disabled).
        """
        if self.timeout_s is None:
            return fn(*args, **kwargs)
        with self._lock:
            if self._tripped_at is not None:
                if time.monotonic() - self._tripped_at < self.cooldown_s:
                    raise SolveTimeout(
                        "watchdog tripped; accelerator considered down for "
                        f"{self.cooldown_s}s (or until reset())"
                    )
                self._tripped_at = None  # cooldown over — probe again

        outcome: Dict[str, Any] = {}
        done = threading.Event()

        def run() -> None:
            try:
                outcome["value"] = fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                outcome["exc"] = exc
            finally:
                done.set()

        worker = threading.Thread(target=run, name="klba-solve", daemon=True)
        worker.start()
        if not done.wait(self.timeout_s):
            with self._lock:
                self._tripped_at = time.monotonic()
            LOGGER.warning(
                "device solve exceeded %.1fs; abandoning call and marking "
                "accelerator down",
                self.timeout_s,
            )
            raise SolveTimeout(f"device solve exceeded {self.timeout_s}s")
        if "exc" in outcome:
            raise outcome["exc"]
        return outcome["value"]
