"""Failure detection for accelerator calls: per-solver circuit breakers.

The reference's failure model is exception propagation (broker RPCs abort
the rebalance, SURVEY §2.4.9) — but an accelerator behind a
tunnel/sidecar can also *hang* (observed in practice: a wedged transport
makes even device enumeration block forever).  A consumer-group rebalance
must never block on the accelerator past its rebalance timeout, so device
solves run under a watchdog: the call executes in a daemon worker thread
and, on timeout, the caller falls back to the host path while the stuck
call is abandoned (threads blocked in a wedged RPC cannot be force-killed
from Python; abandoning is the correct containment — the daemon thread
dies with the process and later calls go straight to the fallback).

Failure domains are tracked PER KEY (one circuit breaker per solver /
subsystem), because a wedged Sinkhorn compile says nothing about the
rounds kernel's health: one slow solver must not banish every solver for
the full cooldown.  Each breaker is a standard three-state circuit:

* **closed** — calls run under the deadline.  A timeout trips the
  breaker immediately; ``failure_threshold`` CONSECUTIVE exceptions trip
  it too (a repeatedly-raising device is as dead as a hanging one — the
  reference-style raise path was previously never counted).
* **open** — calls fail fast with :class:`SolveTimeout` (host fallback)
  for ``cooldown_s``; no fresh worker threads pile up behind the wedge.
* **half-open** — after the cooldown, exactly ONE caller is admitted as
  the probe; concurrent callers keep failing fast until the probe
  resolves.  (The previous design cleared the trip under the lock and
  let every blocked waiter spawn a probe thread against the possibly
  still-wedged device — a thundering herd of abandoned threads.)  Probe
  success closes the breaker; probe failure re-opens it for a fresh
  cooldown.

``clock`` is injectable so cooldown/half-open transitions are unit
testable without real sleeps.  Worker threads capture ``BaseException``
but re-raise only ``Exception`` through the normal path: a true
``BaseException`` (e.g. ``KeyboardInterrupt`` delivered on the worker)
is logged critically and re-raised deliberately on the caller side, so
``except Exception`` boundaries (the service's wire handler) let it
propagate instead of swallowing a shutdown signal into an error
response.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, TypeVar

from . import metrics
from . import trace as trace_mod
from .observability import note_breaker_trip

LOGGER = logging.getLogger(__name__)

# Registry series (utils/metrics): completed-call latency per breaker
# key, plus timeout / fail-fast-rejection counters — the queryable
# aggregate behind every Watchdog instance.
_SOLVE_MS = "klba_solve_duration_ms"
_TIMEOUTS = "klba_solve_timeouts_total"
_REJECTED = "klba_solve_rejected_total"

T = TypeVar("T")

_UNSET = object()

# Worker-thread deadline note: Watchdog.call stamps each worker with
# (clock, abandon_at) before running the callable, so code the worker
# parks in (the megabatch coalescer's future wait) can hand downstream
# threads an answer to "has my caller already abandoned me?".
_worker_tls = threading.local()


def capture_abandon_check() -> Optional[Callable[[], bool]]:
    """Capture the calling watchdog worker's deadline as a zero-arg
    predicate: True once the caller's deadline has passed (the caller
    has certainly timed out and abandoned this thread — its result
    would be discarded).  None when the calling thread is not a watched
    worker (no deadline, nothing to abandon).  The token is safe to
    evaluate from any thread: the coalescer's flusher uses it to DROP a
    parked submission whose submitter is already gone (see
    ops/coalesce)."""
    note = getattr(_worker_tls, "deadline", None)
    if note is None:
        return None
    clock, abandon_at = note
    return lambda: clock() > abandon_at

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


class SolveTimeout(Exception):
    """Raised when a watched call exceeds its deadline, its breaker is
    open, or its deadline budget is already exhausted."""


class SolveRejected(SolveTimeout):
    """Fail-fast subtype: the call was rejected WITHOUT running (breaker
    open, probe already in flight, or budget exhausted) — the device was
    never touched, so callers holding warm state tied to the callable
    (the streaming engines) know that state is still intact."""


class _Breaker:
    """One failure domain's state (guarded by the owning Watchdog's lock)."""

    __slots__ = (
        "state", "tripped_at", "consecutive_failures", "trips",
        "probe_in_flight",
    )

    def __init__(self):
        self.state = STATE_CLOSED
        self.tripped_at: Optional[float] = None
        self.consecutive_failures = 0
        self.trips = 0
        self.probe_in_flight = False


class Watchdog:
    """Runs callables with a deadline on abandonable daemon threads,
    with one circuit breaker per ``key`` (see module docstring).

    Deliberately NOT a ThreadPoolExecutor: the executor's atexit hook JOINS
    its workers, so a process that abandoned a hung solve would block at
    shutdown for the full hang.  A bare daemon thread dies with the process.
    """

    def __init__(
        self,
        timeout_s: Optional[float],
        cooldown_s: float = 300.0,
        failure_threshold: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.timeout_s = timeout_s
        self.cooldown_s = cooldown_s
        self.failure_threshold = int(failure_threshold)
        self._clock = clock
        self._breakers: Dict[str, _Breaker] = {}
        self._lock = threading.Lock()

    # -- state inspection --------------------------------------------------

    def _breaker(self, key: str) -> _Breaker:
        """Caller must hold ``self._lock``."""
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = _Breaker()
        return br

    def _effective_state(self, br: _Breaker) -> str:
        """THE cooldown-expiry rule, in one place (caller holds the
        lock): an OPEN breaker whose cooldown has elapsed reports
        half-open — the next call will be the probe."""
        if br.state == STATE_OPEN and (
            br.tripped_at is None
            or self._clock() - br.tripped_at >= self.cooldown_s
        ):
            return STATE_HALF_OPEN
        return br.state

    @property
    def tripped(self) -> bool:
        """True while ANY breaker is open within its cooldown."""
        with self._lock:
            return any(
                self._effective_state(br) == STATE_OPEN
                for br in self._breakers.values()
            )

    def state(self, key: str = "device") -> str:
        """The breaker's current state name (cooldown expiry applied)."""
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                return STATE_CLOSED
            return self._effective_state(br)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-key breaker snapshot for the service ``stats`` surface."""
        with self._lock:
            return {
                key: {
                    "state": self._effective_state(br),
                    "trips": br.trips,
                    "consecutive_failures": br.consecutive_failures,
                }
                for key, br in self._breakers.items()
            }

    def reset(self) -> None:
        """Close every breaker immediately (operator action)."""
        with self._lock:
            for br in self._breakers.values():
                br.state = STATE_CLOSED
                br.tripped_at = None
                br.consecutive_failures = 0
                br.probe_in_flight = False

    # -- lifecycle snapshot (utils/snapshot; DEPLOYMENT.md "Restarts") -----

    def export_state(self) -> Dict[str, Dict[str, Any]]:
        """Host-durable view of every breaker for the lifecycle
        snapshot.  ``tripped_at`` is a monotonic instant that dies with
        the process, so an open breaker exports its REMAINING cooldown
        instead — the restored breaker resumes the remainder, not a
        fresh full cooldown (a restart must not extend a sidelining)
        and not an instant close (a restart must not reset a wedged
        device's quarantine)."""
        with self._lock:
            now = self._clock()
            out: Dict[str, Dict[str, Any]] = {}
            for key, br in self._breakers.items():
                remaining = 0.0
                if br.state == STATE_OPEN and br.tripped_at is not None:
                    remaining = max(
                        0.0, self.cooldown_s - (now - br.tripped_at)
                    )
                out[key] = {
                    "state": self._effective_state(br),
                    "cooldown_remaining_s": remaining,
                    "consecutive_failures": br.consecutive_failures,
                    "trips": br.trips,
                }
            return out

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Adopt exported breaker state after a restart: an open
        breaker resumes the remainder of its cooldown (clamped to this
        process's configured cooldown), failure/trip counters carry
        over, and the half-open probe slot is always reset (a probe
        never survives a process).  Malformed entries are discarded
        per key — a corrupt breaker record must not cost the others."""
        with self._lock:
            now = self._clock()
            for key, info in dict(state or {}).items():
                try:
                    br = self._breaker(str(key))
                    br.consecutive_failures = int(
                        info.get("consecutive_failures", 0)
                    )
                    br.trips = int(info.get("trips", 0))
                    remaining = min(
                        max(float(info.get("cooldown_remaining_s", 0.0)),
                            0.0),
                        self.cooldown_s,
                    )
                    if info.get("state") == STATE_OPEN and remaining > 0:
                        br.state = STATE_OPEN
                        br.tripped_at = now - (self.cooldown_s - remaining)
                    else:
                        br.state = STATE_CLOSED
                        br.tripped_at = None
                    br.probe_in_flight = False
                except (TypeError, ValueError, AttributeError):
                    LOGGER.warning(
                        "discarding malformed breaker snapshot for %r",
                        key, exc_info=True,
                    )

    # -- transitions (hold the lock) --------------------------------------

    def _trip(self, br: _Breaker) -> bool:
        """Returns True when this call opened the breaker.  The caller
        fires :func:`note_breaker_trip` AFTER releasing the lock — the
        trip hook dumps the flight recorder (JSON build, optional file
        write), and holding the process-wide breaker lock through that
        would stall every other thread's fail-fast admission exactly
        during an incident."""
        if br.state == STATE_OPEN:
            # A straggler admitted before the trip fails after it: one
            # incident, one trip — don't inflate the counter or refresh
            # tripped_at (that would silently extend the cooldown).
            return False
        br.state = STATE_OPEN
        br.tripped_at = self._clock()
        br.trips += 1
        br.probe_in_flight = False
        return True

    def _admit(self, key: str) -> bool:
        """Admission control; returns True when this call is the half-open
        probe.  Raises SolveTimeout to fail fast (open breaker, or probe
        already in flight)."""
        with self._lock:
            br = self._breaker(key)
            if br.state == STATE_OPEN:
                if (
                    br.tripped_at is not None
                    and self._clock() - br.tripped_at < self.cooldown_s
                ):
                    raise SolveRejected(
                        f"breaker {key!r} open; failing fast for up to "
                        f"{self.cooldown_s}s (or until reset())"
                    )
                br.state = STATE_HALF_OPEN
                br.probe_in_flight = False
            if br.state == STATE_HALF_OPEN:
                if br.probe_in_flight:
                    # THE thundering-herd fix: one probe, everyone else
                    # fails fast to the host path.
                    raise SolveRejected(
                        f"breaker {key!r} half-open; probe already in flight"
                    )
                br.probe_in_flight = True
                return True
            return False

    def _on_success(self, key: str) -> None:
        with self._lock:
            br = self._breaker(key)
            br.state = STATE_CLOSED
            br.tripped_at = None
            br.consecutive_failures = 0
            br.probe_in_flight = False

    def _on_timeout(self, key: str, probing: bool, truncated: bool) -> None:
        with self._lock:
            br = self._breaker(key)
            br.consecutive_failures += 1
            if truncated and not probing:
                # The deadline was a request's RESIDUAL budget, shorter
                # than the configured timeout: the device was never given
                # its fair window, so missing it is the request's fault —
                # recorded as a failure, but not a trip that would
                # sideline the device for every other request.  (A
                # half-open probe still re-opens: it ran and was
                # abandoned, recovered or not.)
                return
            tripped = self._trip(br)
        if tripped:
            note_breaker_trip(key)

    def _on_exception(self, key: str, probing: bool) -> None:
        tripped = False
        with self._lock:
            br = self._breaker(key)
            br.consecutive_failures += 1
            if probing:
                # A failed probe re-opens immediately — the device did not
                # recover; don't let waiters rediscover that one by one.
                tripped = self._trip(br)
            elif br.consecutive_failures >= self.failure_threshold:
                LOGGER.warning(
                    "breaker %r tripped after %d consecutive exceptions",
                    key, br.consecutive_failures,
                )
                tripped = self._trip(br)
        if tripped:
            note_breaker_trip(key)

    def trip_breaker(self, key: str) -> None:
        """External failure-domain evidence against ``key``'s breaker:
        open it NOW for a full cooldown (half-open probe recovery
        applies as usual).  Used by the resident-state scrubber
        (utils/scrub): repeated quarantines on one stream mean the
        device is corrupting state faster than the heal path restores
        it — as dead as a device that keeps raising.  A direct trip,
        deliberately NOT a consecutive-failure increment: every
        corrupt/heal cycle contains a successful healing epoch that
        would reset that counter, so threshold counting could never
        sideline exactly the repeating pattern escalation exists
        for."""
        with self._lock:
            tripped = self._trip(self._breaker(key))
        if tripped:
            note_breaker_trip(key)

    # -- the watched call --------------------------------------------------

    def call(
        self,
        fn: Callable[..., T],
        *args: Any,
        key: str = "device",
        timeout_s: Any = _UNSET,
        budget_total_s: Optional[float] = None,
        **kwargs: Any,
    ) -> T:
        """Run ``fn`` under the deadline with ``key``'s breaker.

        ``timeout_s`` overrides the configured deadline for THIS call
        (the service's per-request deadline budget shrinks it down the
        degraded-mode ladder); a non-positive override fails fast WITHOUT
        charging the breaker — an exhausted budget is the request's
        fault, not the device's.  With an effective deadline of None the
        call runs inline (watchdog disabled).

        ``budget_total_s`` is the request's INITIAL deadline budget when
        it is smaller than the configured timeout (a per-class SLO
        budget, utils/overload): the timeout-truncation test then
        compares against the request's own full window, so a first-rung
        hang under a 2 s class budget still charges the breaker instead
        of reading as a residual-ladder truncation forever.
        """
        effective = self.timeout_s if timeout_s is _UNSET else timeout_s
        if effective is None:
            return fn(*args, **kwargs)
        if effective <= 0:
            metrics.REGISTRY.counter(_REJECTED, {"key": key}).inc()
            raise SolveRejected(
                f"deadline budget exhausted before calling {key!r}"
            )
        try:
            probing = self._admit(key)
        except SolveRejected:
            metrics.REGISTRY.counter(_REJECTED, {"key": key}).inc()
            raise
        started = self._clock()
        settled = False  # an _on_* transition (or explicit release) ran
        try:
            outcome: Dict[str, Any] = {}
            done = threading.Event()
            # The caller's request scope, carried onto the worker so
            # solve-side telemetry (flight records, guardrail dump
            # triggers) keeps the request id and the one-dump-per-
            # request budget (utils/metrics.adopt_scope).
            scope = metrics.capture_scope()

            def run() -> None:
                # Deadline note for capture_abandon_check(): downstream
                # code this worker parks in can learn when the caller
                # will have abandoned it.
                _worker_tls.deadline = (self._clock, started + effective)
                try:
                    with metrics.adopt_scope(scope):
                        outcome["value"] = fn(*args, **kwargs)
                except BaseException as exc:  # noqa: BLE001 — re-raised below
                    outcome["exc"] = exc
                finally:
                    _worker_tls.deadline = None
                    done.set()

            worker = threading.Thread(
                target=run, name="klba-solve", daemon=True
            )
            worker.start()
            if not done.wait(effective):
                metrics.REGISTRY.counter(_TIMEOUTS, {"key": key}).inc()
                # An abandoned solve is an always-keep trace anomaly:
                # the caller's thread still owns the request scope
                # here (the worker only ADOPTED it).
                trace_mod.mark("timeout")
                # "Truncated" = the ladder handed the device a residual
                # budget well below the request's full window — the
                # configured timeout, or the caller's (smaller) initial
                # deadline budget when a per-class SLO budget capped it.
                # The 0.9 factor absorbs the request-validation time
                # between budget creation and rung 1 (microseconds-to-
                # ms), so a first-rung hang still trips at ~the full
                # deadline.
                window = self.timeout_s
                if budget_total_s is not None and (
                    window is None or budget_total_s < window
                ):
                    window = budget_total_s
                truncated = (
                    window is not None and effective < window * 0.9
                )
                self._on_timeout(key, probing, truncated)
                settled = True
                LOGGER.warning(
                    "%r call exceeded %.1fs (%s); abandoning it",
                    key, effective,
                    "residual budget — breaker not tripped" if truncated
                    else f"breaker open for {self.cooldown_s:.0f}s",
                )
                raise SolveTimeout(f"{key!r} call exceeded {effective}s")
            exc = outcome.get("exc")
            if not isinstance(exc, SolveRejected):
                # A shed parked for its whole class budget before the
                # rejection surfaced — observing it here would turn the
                # solver-latency p99 into park-until-shed time under
                # sustained overload, so only genuine solve attempts
                # feed the series.
                metrics.REGISTRY.histogram(_SOLVE_MS, {"key": key}).observe(
                    (self._clock() - started) * 1000.0
                )
            if exc is None:
                self._on_success(key)
                settled = True
                return outcome["value"]
            if isinstance(exc, SolveRejected):
                # A nested fail-fast rejection surfaced THROUGH the
                # worker (e.g. the coalescer shedding a parked epoch
                # whose SLO deadline expired — ops/coalesce
                # DeadlineShed): the device was never touched, so the
                # breaker must not be charged — an overload shed is the
                # request's fate, not the solver's failure.  The
                # half-open probe slot (if any) is released by the
                # not-settled finally below.
                raise exc
            if isinstance(exc, Exception):
                self._on_exception(key, probing)
                settled = True
                raise exc
            # True BaseException (KeyboardInterrupt, SystemExit) captured
            # on the worker: re-raise it DELIBERATELY on the caller thread
            # so it propagates past `except Exception` boundaries instead
            # of dying silently with the worker — but never count it
            # against the device's breaker.
            LOGGER.critical(
                "%r worker raised %s; propagating on the caller thread",
                key, type(exc).__name__,
            )
            raise exc
        finally:
            if probing and not settled:
                # The probe aborted before any state transition (e.g.
                # worker.start() failed under thread exhaustion, or a
                # BaseException) — release the half-open slot so the
                # breaker cannot wedge in 'probe already in flight'
                # fail-fast forever.
                with self._lock:
                    self._breaker(key).probe_in_flight = False
