"""Unified metrics: registry, request-scoped spans, flight recorder.

Rounds 6-7 grew the system into a sidecar with a warm-resident engine, a
degraded-mode ladder, per-solver circuit breakers, and a fault injector —
and the telemetry for all of that was scattered ad-hoc globals
(``compile_count``, ``static_drift_count``, ``breaker_trip_counts``) plus
per-response dicts that vanish once the socket closes.  This module is
the ONE process-wide home for time-series telemetry; the old entry points
still exist (utils/observability keeps its function signatures) but are
now thin views over the registry.

Three layers:

**Registry** — thread-safe counters, gauges, and fixed-bucket log2
histograms, addressed by ``(name, labels)``.  The hot path is
allocation-lean by construction: every series' storage (the bucket
array, the running count/sum) is preallocated at first registration, a
record is integer adds under the series' own lock, and callers on warm
loops pre-bind the series object once (``registry.histogram(...)``
returns the same child for the same name+labels forever).  Histogram
buckets are log2: bucket ``i`` holds values in ``(2^(i-1), 2^i]``
(bucket 0 holds ``v <= 1``), so recording needs no search — the index
is ``(v - 1).bit_length()`` for integers — and percentile estimates are
bucket upper edges clamped to the observed min/max.  Export is a JSON
snapshot or the Prometheus text exposition.

**Spans** — ``with span("stream.refine"):`` records the block's duration
into ``klba_span_duration_ms{span=...}`` and, when a request scope is
active on the thread, appends a (name, parent, start, duration) entry to
the request's timeline.  The service mints one request id per wire
request (``request_scope``), echoes it in every response envelope, and
tags package log lines emitted on the request thread
(:class:`RequestIdLogFilter`).

**Flight recorder** — a bounded ring of the last N rebalance /
stream-epoch records (stats only — assignment payloads are redacted)
that auto-dumps to JSON whenever a breaker trips, a guardrail fires, or
a request descends past the first ladder rung, so a degraded production
incident is debuggable after the fact without trace-level logging.  At
most one auto-dump per request scope: the first trigger wins (a breaker
trip and the ladder descent it causes are ONE incident).

Clock discipline: every duration here flows through the module clock
(``perf_counter`` by default, injectable for tests).  Package code must
not call ``time.time()`` / ``time.perf_counter()`` directly — lint rule
L012 (tools/lint.py) enforces it; this file and utils/observability.py
(``stopwatch``) are the only exemptions.
"""

from __future__ import annotations

import itertools
import json
import logging
import math
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from . import trace as trace_mod

LOGGER = logging.getLogger(__name__)

# 40 log2 buckets: the last upper edge is 2^39 (~17 years in ms, ~5.5e11
# in raw units) — everything beyond clamps into the final bucket.
NBUCKETS = 40

_LabelsKey = Tuple[Tuple[str, str], ...]


def bucket_index(value: float) -> int:
    """The log2 bucket rule, shared by recording and tests: bucket 0
    holds ``v <= 1`` (including 0 and negatives, which durations and
    counts never produce anyway); bucket ``i`` holds ``(2^(i-1), 2^i]``.
    Exact at integer powers of two: ``2^k`` lands in bucket k,
    ``2^k + 1`` in bucket k+1."""
    if value <= 1:
        return 0
    if isinstance(value, int):
        idx = (value - 1).bit_length()
    else:
        # frexp is exact: v = m * 2^e with 0.5 <= m < 1, so the upper-
        # edge-inclusive bucket is e-1 exactly at powers of two (m=0.5).
        m, e = math.frexp(value)
        idx = e - 1 if m == 0.5 else e
    return idx if idx < NBUCKETS else NBUCKETS - 1


class Counter:
    """Monotonic counter series."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value series."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket log2 histogram series (see :func:`bucket_index`)."""

    __slots__ = (
        "name", "labels", "_lock", "_buckets", "_count", "_sum",
        "_min", "_max",
    )

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._buckets = [0] * NBUCKETS  # preallocated: zero-alloc observe
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value: float) -> None:
        idx = bucket_index(value)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        """Deterministic estimate: the upper edge (``2^i``) of the bucket
        holding the q-quantile observation, clamped to the observed
        [min, max] — never reports a value outside what was recorded."""
        with self._lock:
            if self._count == 0:
                return None
            rank = max(1, math.ceil(q * self._count))
            acc = 0
            for i, c in enumerate(self._buckets):
                acc += c
                if acc >= rank:
                    edge = float(1 << i)
                    return min(max(edge, self._min), self._max)
            return self._max  # unreachable; defensive

    def state(self) -> Dict[str, Any]:
        """Raw series state (buckets included) — the snapshot/delta unit."""
        with self._lock:
            return {
                "buckets": list(self._buckets),
                "count": self._count,
                "sum": self._sum,
                "min": None if self._count == 0 else self._min,
                "max": None if self._count == 0 else self._max,
            }


class Registry:
    """Process-wide, thread-safe home of every metric series.

    ``counter`` / ``gauge`` / ``histogram`` are get-or-create: the same
    (name, labels) always returns the same child object, so hot paths
    pre-bind once and record lock-cheap forever after.  A name is bound
    to exactly one metric type; rebinding is a bug and raises."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._lock = threading.Lock()
        self._types: Dict[str, str] = {}
        self._children: Dict[Tuple[str, _LabelsKey], Any] = {}
        self.clock = clock

    def _child(self, kind: str, cls, name: str,
               labels: Optional[Dict[str, str]]):
        labels = {k: str(v) for k, v in (labels or {}).items()}
        key = (name, tuple(sorted(labels.items())))
        child = self._children.get(key)  # GIL-safe fast path, no lock
        if child is not None:
            if not isinstance(child, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(child).__name__.lower()}"
                )
            return child
        with self._lock:
            child = self._children.get(key)
            if child is None:
                bound = self._types.setdefault(name, kind)
                if bound != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {bound}"
                    )
                child = self._children[key] = cls(name, labels)
        return child

    def counter(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._child("counter", Counter, name, labels)

    def gauge(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._child("gauge", Gauge, name, labels)

    def histogram(self, name: str,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._child("histogram", Histogram, name, labels)

    def series(self, name: str) -> List[Any]:
        """Every child registered under ``name`` (label-sorted order)."""
        with self._lock:
            return [
                child for (n, _), child in sorted(
                    self._children.items(), key=lambda kv: kv[0]
                ) if n == name
            ]

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able full state: per name, its type and every series
        (labels + value, histograms with buckets and p50/p99)."""
        with self._lock:
            items = sorted(self._children.items(), key=lambda kv: kv[0])
            types = dict(self._types)
        out: Dict[str, Any] = {}
        for (name, _), child in items:
            entry = out.setdefault(
                name, {"type": types[name], "series": []}
            )
            if isinstance(child, Histogram):
                st = child.state()
                st["p50"] = child.percentile(0.50)
                st["p99"] = child.percentile(0.99)
                entry["series"].append({"labels": child.labels, **st})
            else:
                entry["series"].append(
                    {"labels": child.labels, "value": child.value}
                )
        return out

    def prometheus(self, snap: Optional[Dict[str, Any]] = None) -> str:
        """The Prometheus text exposition (version 0.0.4): ``# TYPE``
        headers, cumulative ``_bucket{le=...}`` series ending at
        ``+Inf``, ``_sum``/``_count`` per histogram series.  Pass an
        existing :meth:`snapshot` to render both views from ONE registry
        walk (the wire ``metrics`` method does)."""
        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            parts = [
                f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
            ]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: List[str] = []
        if snap is None:
            snap = self.snapshot()
        for name, entry in snap.items():
            lines.append(f"# TYPE {name} {entry['type']}")
            for s in entry["series"]:
                labels = s["labels"]
                if entry["type"] != "histogram":
                    value = s["value"]
                    lines.append(f"{name}{fmt_labels(labels)} {value}")
                    continue
                acc = 0
                for i, c in enumerate(s["buckets"]):
                    if c == 0 and i != NBUCKETS - 1:
                        # skip empty interior buckets; cumulative values
                        # stay correct and the exposition stays readable
                        continue
                    acc = sum(s["buckets"][: i + 1])
                    le = fmt_labels(labels, f'le="{1 << i}"')
                    lines.append(f"{name}_bucket{le} {acc}")
                inf = fmt_labels(labels, 'le="+Inf"')
                lines.append(f"{name}_bucket{inf} {s['count']}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {s['sum']}")
                lines.append(
                    f"{name}_count{fmt_labels(labels)} {s['count']}"
                )
        return "\n".join(lines) + "\n"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def histogram_deltas(
    before: Dict[str, Any], after: Dict[str, Any]
) -> Dict[str, Any]:
    """Per-series p50/p99/count of the OBSERVATIONS MADE BETWEEN two
    :meth:`Registry.snapshot` calls (bucket-wise subtraction) — how
    bench.py embeds per-config histogram percentiles without resetting
    the process-wide registry.  Series with no new observations are
    omitted."""
    out: Dict[str, Any] = {}
    for name, entry in after.items():
        if entry["type"] != "histogram":
            continue
        prior = {
            _series_key(s): s
            for s in before.get(name, {}).get("series", [])
        }
        for s in entry["series"]:
            b = prior.get(_series_key(s))
            buckets = list(s["buckets"])
            count, total = s["count"], s["sum"]
            if b is not None:
                buckets = [x - y for x, y in zip(buckets, b["buckets"])]
                count -= b["count"]
                total -= b["sum"]
            if count <= 0:
                continue
            key = name + "".join(
                f"{{{k}={v}}}" for k, v in sorted(s["labels"].items())
            )
            out[key] = {
                "count": count,
                "sum": total,
                "p50": _delta_percentile(buckets, count, 0.50),
                "p99": _delta_percentile(buckets, count, 0.99),
            }
    return out


def _series_key(s: Dict[str, Any]) -> _LabelsKey:
    return tuple(sorted(s["labels"].items()))


def _delta_percentile(buckets: List[int], count: int, q: float) -> float:
    rank = max(1, math.ceil(q * count))
    acc = 0
    for i, c in enumerate(buckets):
        acc += c
        if acc >= rank:
            return float(1 << i)
    return float(1 << (NBUCKETS - 1))


# --- the process-wide registry ------------------------------------------

REGISTRY = Registry()


def registry() -> Registry:
    return REGISTRY


# --- request scopes + spans ---------------------------------------------

_tls = threading.local()
# itertools.count, not a locked cell: next() is one C-level call
# (GIL-atomic) and a request id is minted per wire request inside the
# <1% epoch budget.
_req_seq = itertools.count(1)


class _RequestCtx:
    __slots__ = (
        "request_id", "spans", "stack", "start", "dumped_cell",
        "trace", "adopt_parent_rec", "device_ms",
    )

    def __init__(
        self,
        request_id: str,
        start: float,
        dumped_cell: Optional[List[bool]] = None,
        trace: Optional[trace_mod.TraceState] = None,
        adopt_parent_rec: Optional[Dict[str, Any]] = None,
    ):
        self.request_id = request_id
        self.spans: List[Dict[str, Any]] = []
        # Open-span stack of span RECORD dicts (innermost last): spans
        # read their parent's name/span_id off the top, device phases
        # accumulate device_ms onto every open record.
        self.stack: List[Dict[str, Any]] = []
        self.start = start
        # One-auto-dump-per-request state, a shared CELL rather than a
        # plain bool: a scope adopted onto a worker thread
        # (:func:`adopt_scope`) shares the cell with its parent, so the
        # incident budget spans both threads.
        self.dumped_cell = (
            dumped_cell if dumped_cell is not None else [False]
        )
        # The trace this scope feeds (shared ACROSS threads by
        # adopt_scope — TraceState mutation is GIL-atomic by design)
        # and, on adopted worker scopes, the capture point's innermost
        # open span RECORD: the worker's spans parent under it (by
        # reference — ids are minted only if the trace is kept).
        self.trace = trace
        self.adopt_parent_rec = adopt_parent_rec
        # This THREAD's device-phase time; folded into the trace at
        # scope teardown (per-thread so concurrent phases never race a
        # float read-modify-write).
        self.device_ms = 0.0


def mint_request_id() -> str:
    return f"req-{os.getpid()}-{next(_req_seq)}"


def current_request_id() -> Optional[str]:
    ctx = getattr(_tls, "ctx", None)
    return ctx.request_id if ctx is not None else None


def current_timeline() -> List[Dict[str, Any]]:
    """The active request's COMPLETED spans so far (empty outside a
    scope)."""
    ctx = getattr(_tls, "ctx", None)
    return list(ctx.spans) if ctx is not None else []


def current_open_spans() -> List[str]:
    """The active request's still-open span NAMES, outermost first —
    at incident time (a dump) this names the phase the request died in."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return []
    return [rec["name"] for rec in ctx.stack]


def current_trace() -> Optional[trace_mod.TraceState]:
    """The active scope's trace state (None outside a traced scope)."""
    ctx = getattr(_tls, "ctx", None)
    return ctx.trace if ctx is not None else None


def current_trace_id() -> Optional[str]:
    tr = current_trace()
    return tr.trace_id if tr is not None else None


def current_traceparent() -> Optional[str]:
    """The W3C context an OUTBOUND hop should carry: the active trace
    id plus the innermost open span's id (falling back to the adopted
    parent, then the trace root) — so the remote segment parents under
    the span that made the call."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or ctx.trace is None:
        return None
    stack = ctx.stack
    rec = stack[-1] if stack else ctx.adopt_parent_rec
    if rec is None:
        return ctx.trace.traceparent()
    # Span ids are minted lazily (kept traces only) — an outbound hop
    # forces the id here so the remote segment has a real parent.
    sid = rec.get("span_id")
    if sid is None:
        sid = rec["span_id"] = trace_mod.mint_span_id()
    return ctx.trace.traceparent(sid)


def _teardown_ctx(ctx: _RequestCtx, finish: bool) -> None:
    """Fold one thread's spans/device time into the shared trace; the
    scope that OWNS the trace (request_scope / finish_scope) also runs
    the tail-sampling decision."""
    tr = ctx.trace
    if tr is None:
        return
    if finish:
        # Decide-first: a mint-doomed healthy trace (the dominant
        # outcome at production sample rates) exits via fast_drop
        # without duration math, span absorption, or span-id minting;
        # only kept/undecided traces pay the full finish.
        coll = trace_mod.COLLECTOR
        if coll.fast_drop(tr):
            return
        duration_ms = (REGISTRY.clock() - ctx.start) * 1000.0
        coll.finish(
            tr, duration_ms, spans=ctx.spans, device_ms=ctx.device_ms
        )
    else:
        tr.absorb(ctx.spans, ctx.device_ms)


class _RequestScope:
    """The :func:`request_scope` context manager, hand-rolled for the
    same reason as :class:`_Span`: the ``@contextmanager`` generator
    protocol costs ~2x per enter/exit, and the service opens one of
    these per wire request inside the <1% epoch budget."""

    __slots__ = ("_request_id", "_traceparent", "_kind", "_root_name",
                 "_ctx")

    def __init__(
        self,
        request_id: Optional[str],
        traceparent: Optional[str],
        kind: str,
        root_name: Optional[str],
    ):
        self._request_id = request_id
        self._traceparent = traceparent
        self._kind = kind
        self._root_name = root_name

    def __enter__(self) -> str:
        outer = getattr(_tls, "ctx", None)
        if outer is not None:
            # Nested scope: flatten — the outermost wins, and __exit__
            # must not tear down a ctx it does not own.
            self._ctx = None
            return outer.request_id
        rid = self._request_id or mint_request_id()
        # Positional calls: this pair runs per wire request inside the
        # <1% epoch budget, and CPython kwargs cost a dict build.
        ctx = self._ctx = _RequestCtx(
            rid, REGISTRY.clock(), None,
            trace_mod.TraceState(
                self._kind, self._root_name, rid, self._traceparent
            ),
        )
        _tls.ctx = ctx
        return rid

    def __exit__(self, *exc: Any) -> bool:
        ctx = self._ctx
        if ctx is not None:
            _tls.ctx = None
            _teardown_ctx(ctx, finish=True)
        return False


def request_scope(
    request_id: Optional[str] = None,
    traceparent: Optional[str] = None,
    kind: str = "request",
    root_name: Optional[str] = None,
) -> _RequestScope:
    """Scope a wire request: mints (or adopts) a request id, roots a
    trace (adopting ``traceparent``'s trace id when the caller sent a
    valid one — the cross-process join), carries the span timeline, and
    bounds the one-auto-dump-per-request rule.  ``kind``/``root_name``
    name self-rooted non-wire traces (``background`` scrubber passes
    and snapshot writes, ``client`` lag reads).  Nested scopes are
    flattened: the outermost wins.  Scope exit runs the tail-sampling
    retention decision on the finished trace."""
    return _RequestScope(request_id, traceparent, kind, root_name)


def capture_scope() -> Optional[_RequestCtx]:
    """Opaque token of the calling thread's active request scope (None
    outside one) — hand it to a worker thread for :func:`adopt_scope`."""
    return getattr(_tls, "ctx", None)


@contextmanager
def adopt_scope(token: Optional[_RequestCtx]) -> Iterator[Optional[str]]:
    """Join a captured request scope from ANOTHER thread (the watchdog
    runs solves on abandonable workers; without this, engine-side flight
    records would lose the request id and engine-side auto-dump triggers
    would bypass the one-dump-per-request cap).  The worker gets its OWN
    span timeline — the parent may abandon the worker and dump while it
    still runs, so sharing the parent's mutable span list would race —
    but shares the request id, the dump-dedup cell, and the TRACE: the
    worker's spans parent under the capture point's innermost open span
    and land in the same tree.  The adopting side never finishes the
    trace — the owning scope's exit does."""
    if token is None or getattr(_tls, "ctx", None) is not None:
        yield current_request_id()
        return
    adopt_parent = None
    if token.trace is not None:
        # Best-effort snapshot: the capturing thread is normally parked
        # in watchdog.call, but an abandoning parent may already be
        # unwinding its stack — a copy keeps the read safe either way.
        # The adoption point is the capture's innermost open span
        # RECORD (ids stay lazy until the trace is kept).
        stack = list(token.stack)
        adopt_parent = stack[-1] if stack else token.adopt_parent_rec
    ctx = _RequestCtx(
        token.request_id, REGISTRY.clock(),
        dumped_cell=token.dumped_cell,
        trace=token.trace,
        adopt_parent_rec=adopt_parent,
    )
    _tls.ctx = ctx
    try:
        yield ctx.request_id
    finally:
        _tls.ctx = None
        _teardown_ctx(ctx, finish=False)


def begin_scope(
    kind: str = "wave",
    root_name: Optional[str] = None,
    request_id: Optional[str] = None,
) -> _RequestCtx:
    """Mint a scope token WITHOUT installing it on any thread — the
    coalescer's unit of work is a wave that spans the flusher thread
    (dispatch) and a readback worker, with no single ``with`` block
    covering both.  Each participating thread joins via
    :func:`adopt_scope`; :func:`finish_scope` closes the trace exactly
    once when the wave's last act (the readback) completes."""
    rid = request_id or mint_request_id()
    return _RequestCtx(
        rid, REGISTRY.clock(),
        trace=trace_mod.TraceState(
            kind=kind, root_name=root_name, request_id=rid,
        ),
    )


def finish_scope(token: Optional[_RequestCtx]) -> None:
    """Run the retention decision for a :func:`begin_scope` token (the
    token's own span list is empty — every participating thread already
    absorbed its spans at ``adopt_scope`` exit)."""
    if token is not None:
        _teardown_ctx(token, finish=True)


# Per-name cache of the span-duration histogram children: the span
# enter/exit pair sits inside the warm no-op epoch's <1% overhead
# budget, so the label-dict build + sorted-tuple hash of a registry
# lookup is paid once per span name, not once per epoch.
_span_hists: Dict[str, Histogram] = {}


def _span_hist(name: str) -> Histogram:
    h = _span_hists.get(name)
    if h is None:
        h = _span_hists[name] = REGISTRY.histogram(
            "klba_span_duration_ms", {"span": name}
        )
    return h


class _Span:
    """``with span("stream.refine") as rec:`` — times the block into
    ``klba_span_duration_ms{span=name}`` and the request timeline.
    Inside a request scope ``rec`` is the timeline record
    (``duration_ms`` filled at exit; callers may attach extra stats-only
    fields); outside one it is None — only the histogram is fed, and the
    timeline dict is never built (the warm bench loop runs scope-free
    inside the <1% epoch budget).  A hand-rolled context manager, not
    ``@contextmanager``: the generator protocol costs ~2x as much per
    enter/exit and this runs per warm epoch."""

    __slots__ = ("name", "rec", "_start", "_ctx")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> Optional[Dict[str, Any]]:
        ctx = getattr(_tls, "ctx", None)
        self._ctx = ctx
        if ctx is not None:
            parent = ctx.stack[-1] if ctx.stack else None
            rec = self.rec = {
                "name": self.name,
                "parent": parent["name"] if parent is not None else None,
                "duration_ms": 0.0,
            }
            if ctx.trace is not None:
                # The causal tree, deferred: the parent travels by
                # REFERENCE (innermost open span, else the thread's
                # adoption point, else None = the trace root) and real
                # ids are minted only if the trace is KEPT
                # (trace._resolve_span_ids) — a dropped healthy trace
                # never pays for id minting on the warm path.
                rec["_parent_rec"] = (
                    parent if parent is not None else ctx.adopt_parent_rec
                )
            ctx.stack.append(rec)
        else:
            self.rec = None
        self._start = REGISTRY.clock()
        return self.rec

    def __exit__(self, *exc) -> bool:
        dur = (REGISTRY.clock() - self._start) * 1000.0
        ctx = self._ctx
        if ctx is not None:
            rec = self.rec
            rec["duration_ms"] = dur
            ctx.stack.pop()
            rec["start_ms"] = (self._start - ctx.start) * 1000.0
            ctx.spans.append(rec)
        _span_hist(self.name).observe(dur)
        return False


def span(name: str) -> _Span:
    return _Span(name)


# Per-phase device timing for the kernel plane (the linear-OT solve's
# h2d / duals / rounding and the streaming refine readback).  Same
# cached-child pattern as the span histograms — these wrap device
# dispatches on serving paths.
_device_phase_hists: Dict[str, Histogram] = {}


def _device_phase_hist(phase: str) -> Histogram:
    h = _device_phase_hists.get(phase)
    if h is None:
        h = _device_phase_hists[phase] = REGISTRY.histogram(
            "klba_device_phase_ms", {"phase": phase}
        )
    return h


class _DevicePhase:
    """``with device_phase("duals"):`` — wall-clock the enclosed DEVICE
    work into ``klba_device_phase_ms{phase=...}``.  The contract is on
    the CALLER: the block must end with the relevant buffers blocked on
    (``jax.block_until_ready``) or fetched, otherwise the async
    dispatch returns immediately and the phase under-reports.  Phases
    in production: ``h2d`` (host-to-device transfer of the solve
    inputs), ``duals`` (the mirror-prox executable), ``rounding`` (the
    rounding/refine-portfolio executable), ``refine`` (the streaming
    refine step INCLUDING its digest readback — documented in
    DEPLOYMENT.md "Kernel plane"), ``megabatch`` (the coalescer's
    locked/restacked wave readback).  Inside a traced scope the phase
    additionally accumulates ``device_ms`` onto every OPEN span record,
    so epoch spans carry ``{host_ms: duration_ms, device_ms}`` and the
    ROADMAP's "tunnel-confounded" host timings become separable."""

    __slots__ = ("phase", "_start", "_ctx")

    def __init__(self, phase: str):
        self.phase = phase

    def __enter__(self) -> "_DevicePhase":
        self._ctx = getattr(_tls, "ctx", None)
        self._start = REGISTRY.clock()
        return self

    def __exit__(self, *exc) -> bool:
        dur = (REGISTRY.clock() - self._start) * 1000.0
        ctx = self._ctx
        if ctx is not None:
            for rec in ctx.stack:
                rec["device_ms"] = rec.get("device_ms", 0.0) + dur
            ctx.device_ms += dur
        _device_phase_hist(self.phase).observe(dur)
        return False


def device_phase(phase: str) -> _DevicePhase:
    return _DevicePhase(phase)


class RequestIdLogFilter(logging.Filter):
    """Echo the active request id on log lines: attach to a HANDLER you
    own and every record emitted on a request thread grows a
    `` request_id=...`` suffix plus a ``request_id`` attribute for
    structured formatters."""

    def filter(self, record: logging.LogRecord) -> bool:
        _tag_record(record)
        return True


def _tag_record(
    record: logging.LogRecord,
    prefix: str = "kafka_lag_based_assignor_tpu",
) -> logging.LogRecord:
    rid = current_request_id()
    record.request_id = rid or "-"
    if (
        rid is not None
        and record.name.startswith(prefix)
        and "request_id=" not in str(record.msg)
    ):
        # Appending AFTER the %-format string is safe: the original
        # placeholders still line up with record.args.
        record.msg = f"{record.msg} request_id={rid}"
    return record


_factory_installed = [False]


def install_log_request_ids(
    logger_name: str = "kafka_lag_based_assignor_tpu",
) -> None:
    """Idempotently tag every PACKAGE log record with the active request
    id.  Installed as a log-record factory, not a logger filter: logger
    filters are not inherited by child loggers (``...tpu.service`` et
    al. would bypass a filter on the package root), while the factory
    sees every record at creation.  Non-package records only gain the
    ``request_id`` attribute, their message is untouched."""
    if _factory_installed[0]:
        return
    old_factory = logging.getLogRecordFactory()

    def factory(*args, **kwargs):
        return _tag_record(old_factory(*args, **kwargs), logger_name)

    logging.setLogRecordFactory(factory)
    _factory_installed[0] = True


# --- flight recorder -----------------------------------------------------

ENV_FLIGHT_DIR = "KLBA_FLIGHT_DIR"

# Guards the one-auto-dump-per-request test-and-set (the dedup cell is
# shared across threads by adopt_scope).
_dedup_lock = threading.Lock()

#: Keys stripped from flight records: dumps are stats-only — assignment
#: payloads and member/topic identities never leave the process this way.
_REDACTED_KEYS = frozenset(
    {"assignments", "assignment", "members", "subscriptions",
     "member_total_lag", "member_partition_count", "per_topic", "topics"}
)


def _redact(obj: Any) -> Any:
    if isinstance(obj, dict):
        if _REDACTED_KEYS.isdisjoint(obj) and not any(
            isinstance(v, (dict, list, tuple)) or k.startswith("_")
            for k, v in obj.items()
        ):
            # Flat, clean dict (the per-epoch hot case): nothing to
            # strip, no copy.  The recorder takes ownership of records,
            # so aliasing the caller's dict is safe by contract.
            return obj
        # Underscore keys are in-process plumbing (a span record's
        # ``_parent_rec`` reference), never export material.
        return {
            k: _redact(v) for k, v in obj.items()
            if k not in _REDACTED_KEYS and not k.startswith("_")
        }
    if isinstance(obj, (list, tuple)):
        return [_redact(v) for v in obj]
    return obj


class FlightRecorder:
    """Bounded ring of the last N rebalance / stream-epoch records with
    trigger-driven JSON dumps (see the module docstring).

    ``dump_dir`` (default from ``KLBA_FLIGHT_DIR``, unset = in-memory
    only) receives dump files; the last ``keep_dumps`` dumps are also
    retained in memory for tests and the wire ``metrics`` method.  Disk
    usage is bounded two ways — a sustained outage (breaker open, every
    request descending the ladder) must not fill the log volume:
    filenames rotate modulo ``keep_files`` (``flight-<seq % K>.json``;
    the payload's ``dump_seq`` disambiguates), and at most one FILE is
    written per ``disk_min_interval_s`` (skipped dumps stay in memory
    and in the ``klba_flight_dumps_total`` counter)."""

    def __init__(
        self,
        capacity: int = 256,
        dump_dir: Optional[str] = None,
        keep_dumps: int = 8,
        registry_: Optional[Registry] = None,
        keep_files: int = 64,
        disk_min_interval_s: float = 30.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        self.capacity = capacity
        self.dump_dir = (
            dump_dir if dump_dir is not None
            else os.environ.get(ENV_FLIGHT_DIR)
        )
        self.keep_dumps = keep_dumps
        self.keep_files = max(1, int(keep_files))
        self.disk_min_interval_s = disk_min_interval_s
        self._registry = registry_ or REGISTRY
        self._ring: List[Optional[Dict[str, Any]]] = [None] * capacity
        self._idx = 0
        self._total = 0
        self._lock = threading.Lock()
        self._dump_seq = 0
        self._last_disk_dump: Optional[float] = None
        self.dumps: List[Dict[str, Any]] = []

    def record(self, kind: str, rec: Dict[str, Any]) -> None:
        """Append one record; O(1), ring-bounded.  The recorder takes
        ownership of ``rec`` (it is annotated in place, no copy).
        Redaction happens at DUMP time, not here — recording runs once
        per warm epoch inside the <1% overhead budget, dumping runs once
        per incident."""
        rec["kind"] = kind
        ctx = getattr(_tls, "ctx", None)
        if ctx is not None:
            if "request_id" not in rec:
                rec["request_id"] = ctx.request_id
            # Satellite of the tracing plane: every flight record made
            # inside a traced scope names its trace, so an incident
            # dump links straight to the kept trace.
            if ctx.trace is not None and "trace_id" not in rec:
                rec["trace_id"] = ctx.trace.trace_id
        with self._lock:
            rec["seq"] = self._total
            self._ring[self._idx] = rec
            self._idx = (self._idx + 1) % self.capacity
            self._total += 1

    def records(self) -> List[Dict[str, Any]]:
        """Retained records, oldest first."""
        with self._lock:
            tail = self._ring[self._idx:] + self._ring[: self._idx]
            return [r for r in tail if r is not None]

    def snapshot(self) -> List[Dict[str, Any]]:
        """REDACTED copies of the retained records, oldest first — the
        wire dump unit for the per-stream rings (stats only leave the
        process, same rule as :meth:`dump`; copies, so the live ring
        dicts are never handed out)."""
        return [_redact(dict(r)) for r in self.records()]

    def clear(self) -> None:
        """Drop the retained records (operator action after a dump).
        ``seq`` numbering stays monotonic so post-clear records are
        orderable against an earlier dump."""
        with self._lock:
            self._ring = [None] * self.capacity
            self._idx = 0

    def auto_dump(self, reason: str,
                  detail: Optional[Dict[str, Any]] = None) -> bool:
        """Trigger hook (breaker trip / guardrail / ladder descent): at
        most ONE dump per request scope — a trip and the fallback it
        causes are one incident.  Returns True when a dump was written."""
        ctx = getattr(_tls, "ctx", None)
        if ctx is not None:
            # Locked test-and-set: the cell is shared with watchdog
            # worker threads (adopt_scope), and an abandoned worker's
            # guardrail trigger can race the parent's ladder trigger —
            # one incident must stay one dump even then.
            with _dedup_lock:
                if ctx.dumped_cell[0]:
                    return False
                ctx.dumped_cell[0] = True
        self.dump(reason, detail)
        return True

    def dump(self, reason: str,
             detail: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Unconditional dump (operator action / trigger hook)."""
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        payload = {
            "reason": reason,
            "dump_seq": seq,
            "request_id": current_request_id(),
            "trace_id": current_trace_id(),
            "in_flight_spans": _redact(current_timeline()),
            "open_spans": current_open_spans(),
            "detail": _redact(detail) if detail else None,
            # Redacted HERE (stats only leave the process), so the hot
            # per-epoch record path stays copy-free.
            "records": [_redact(r) for r in self.records()],
        }
        now = self._registry.clock()
        with self._lock:
            self.dumps.append(payload)
            del self.dumps[: -self.keep_dumps]
            write_file = bool(self.dump_dir) and (
                self._last_disk_dump is None
                or now - self._last_disk_dump >= self.disk_min_interval_s
            )
            if write_file:
                self._last_disk_dump = now
        self._registry.counter(
            "klba_flight_dumps_total", {"reason": reason}
        ).inc()
        if write_file:
            try:
                # Durable writes go through the atomic helper (tmp +
                # rename; lint L015): an incident dump racing a crash
                # must never leave a torn file for the post-mortem.
                # Imported lazily — utils/snapshot imports this module
                # for its telemetry.
                from .snapshot import atomic_write_bytes

                path = os.path.join(
                    self.dump_dir,
                    f"flight-{seq % self.keep_files}.json",
                )
                # noqa: L017 below — a flight dump is per-instance
                # post-mortem evidence, never adoptable warm state: no
                # replacement reads it back, so backend CAS/fencing
                # has nothing to police here (atomicity via L015's
                # helper is all it needs).
                atomic_write_bytes(  # noqa: L017
                    path,
                    json.dumps(
                        payload, indent=2, sort_keys=True
                    ).encode("utf-8"),
                )
            except OSError:
                LOGGER.warning(
                    "flight-recorder dump to %s failed", self.dump_dir,
                    exc_info=True,
                )
        LOGGER.warning(
            "flight-recorder dump #%d (reason=%s, records=%d)",
            seq, reason, len(payload["records"]),
        )
        return payload

    def dump_count(self) -> int:
        with self._lock:
            return self._dump_seq

    def last_dump(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.dumps[-1] if self.dumps else None


FLIGHT = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return FLIGHT
