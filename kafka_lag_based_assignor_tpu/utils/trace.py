"""Causal tracing: W3C-style context, span trees, tail sampling.

Round 8 gave every wire request a flat ``request_id``; rounds 9-21 grew
the request path into five async hops — wire -> overload admission ->
coalescer megabatch waves -> watchdog worker threads -> federated peer
RPCs — and a flat id cannot say *which* wave a degraded epoch parked
behind or *which* peer round stalled.  This module is the causal layer
on top of utils/metrics: trace ids minted at the wire edge, a real
parent/child span tree (metrics._Span records ``span_id``/``parent_id``
when the scope carries a :class:`TraceState`), cross-boundary
propagation, and anomaly-biased tail retention.

**Context format** — W3C ``traceparent``: ``00-<32 hex trace_id>-
<16 hex span_id>-01`` (55 chars, version 00, sampled flag fixed at 01;
:func:`parse_traceparent` is strict and returns None on ANY deviation —
the federated wire whitelist depends on that).  Span ids are minted as
``(40 random process bits | 24-bit counter)`` so two sidecars joined
into ONE trace (shared trace_id) cannot collide on span ids.

**Propagation map** (DEPLOYMENT.md "Distributed tracing" has the prose
version): clients send ``traceparent`` on the request line and the
service adopts it; ``capture_scope``/``adopt_scope`` carry the SAME
:class:`TraceState` onto watchdog workers (worker spans parent under
the capture point's innermost open span); coalescer waves run as their
own ``wave``-kind traces bidirectionally *linked* to every submitting
request trace; the federated client attaches the current context to
the audited peer envelope so a two-sidecar ``federated_assign`` is one
trace spanning both processes; scrubber passes and snapshot writes run
self-rooted ``background`` traces linked to the streams they touch.

**Tail sampling** — retention decides at trace END (tail), biased by
anomaly marks: a trace that shed, descended the ladder, tripped a
breaker, quarantined, resynced, timed out a solve, or blew the latency
threshold is ALWAYS kept; healthy traces keep at ``sample_rate`` via a
deterministic hash of the trace id (``int(trace_id[:16], 16) / 2**64 <
rate``) — deterministic so a cross-process trace's segments make the
SAME decision in every sidecar, and so tests can pin keep/drop by
choosing ids.  Kept traces live in a bounded in-memory ring (the wire
``{"method": "trace"}`` view), and anomalous ones additionally rotate
to ``KLBA_TRACE_DIR`` JSON files under the flight-dump discipline
(``trace-<seq % keep_files>.json``, min interval between disk writes).

Known limit, by design: sampling is per-process, so a HEALTHY remote
segment of a locally-anomalous trace is only kept when the shared-id
hash admits it (or the remote marked its own anomaly).  Run with
``sample_rate=1.0`` when drilling cross-process reconstruction.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import random
import threading
from typing import Any, Dict, List, Optional, Tuple

LOGGER = logging.getLogger(__name__)

ENV_TRACE_DIR = "KLBA_TRACE_DIR"
ENV_TRACE_SAMPLE = "KLBA_TRACE_SAMPLE"
ENV_TRACE_LATENCY_MS = "KLBA_TRACE_LATENCY_MS"

#: ``00-<32 hex>-<16 hex>-01``
TRACEPARENT_LEN = 55

#: Every anomaly kind :func:`mark` accepts — the always-keep triggers.
ANOMALY_KINDS = frozenset({
    "shed",        # overload admission rejected / deadline-shed a row
    "ladder",      # served from a degraded rung (stream or federated)
    "breaker",     # a solver/peer circuit breaker tripped
    "quarantine",  # integrity digest quarantined resident state
    "resync",      # delta-protocol epoch resync
    "timeout",     # watchdog abandoned a wedged solve
    "latency",     # root duration blew the configured threshold
    "guardrail",   # solve guardrail auto-dump fired
    "error",       # request died with an unhandled error
})

#: The registered span-name catalog — every LITERAL ``span("...")``
#: name in package code must appear here (analyzer rule A005), so a
#: renamed or ad-hoc span cannot silently drift out of dashboards and
#: the DEPLOYMENT.md propagation map.  Scope ROOT names (minted by
#: ``request_scope``/``begin_scope``, not ``span()``) are registered
#: too so the trace view renders from one vocabulary.
SPAN_CATALOG = frozenset({
    # request plane
    "assign.solve",
    "lag.read",
    # streaming engine
    "stream.epoch",
    "stream.cold_solve",
    "stream.sharded_solve",
    "stream.linear_solve",
    "stream.h2d",
    "stream.h2d_delta",
    "stream.refine",
    # coalescer
    "coalesce.window",
    "coalesce.upload",
    "coalesce.dispatch",
    "coalesce.readback",
    # sharded backend
    "sharded.solve",
    "sharded.refine",
    "sharded.linear_duals",
    # federation
    "federation.assign",
    "federation.round",
    "federation.sync",
    "federation.gossip",
    # scope roots
    "request",
    "client",
    "coalesce.wave",
    "scrub.pass",
    "snapshot.write",
})


# --- id minting ----------------------------------------------------------

# 40 random bits fixed per process + a 24-bit counter: unique within a
# process by the counter, across processes by the prefix — two sidecars
# sharing one trace_id (the whole point of propagation) must not mint
# colliding span ids.  The counter is an itertools.count, not a locked
# cell: next() is a single C-level call (GIL-atomic), and this runs
# once per span on serving paths inside the <1% epoch budget.
_SPAN_PREFIX = int.from_bytes(os.urandom(5), "big") << 24
_span_seq = itertools.count(1)

# Trace-id entropy comes from a process-local Mersenne generator, not
# os.urandom: ids need uniqueness and an unbiased sampling hash, not
# cryptographic strength, and getrandbits is one GIL-atomic C call
# where urandom is a syscall — this runs once per wire request inside
# the <1% epoch budget.  Reseeded after fork so sidecar children never
# replay the parent's id stream.
_trace_rng = random.Random(os.urandom(32))


def _reseed_trace_rng() -> None:
    global _trace_rng
    _trace_rng = random.Random(os.urandom(32))


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_reseed_trace_rng)


def mint_trace_id() -> str:
    return format(_trace_rng.getrandbits(128), "032x")


def mint_span_id() -> str:
    return format(_SPAN_PREFIX | (next(_span_seq) & 0xFFFFFF), "016x")


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Any) -> Optional[Tuple[str, str]]:
    """Strict parse -> ``(trace_id, span_id)`` or None.  Anything off —
    wrong type, wrong length, wrong version, non-hex, all-zero ids — is
    rejected, never guessed at: this is the validator the federated
    wire whitelist and the service edge both trust."""
    if not isinstance(value, str) or len(value) != TRACEPARENT_LEN:
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if version != "00" or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def keep_decision(trace_id: str, sample_rate: float) -> bool:
    """The deterministic healthy-trace sampling rule (module
    docstring): shared by every process segment of a trace."""
    if sample_rate >= 1.0:
        return True
    if sample_rate <= 0.0:
        return False
    try:
        frac = int(trace_id[:16], 16) / 2.0 ** 64
    except ValueError:
        return False
    return frac < sample_rate


# --- per-trace state -----------------------------------------------------

#: Hard per-trace bounds (L014): a runaway scope (a span leak in a
#: loop, a wave linking an unbounded submitter set) cannot grow one
#: trace without limit — overflow drops the OLDEST entries, keeping
#: the tail that explains how the trace ENDED.
_MAX_SPANS_PER_TRACE = 512
_MAX_LINKS_PER_TRACE = 256


class TraceState:
    """One trace's accumulating state, shared by every thread a scope
    is adopted onto.  Mutation is GIL-atomic by construction — list
    appends/extends and set adds only — because watchdog workers and
    the request thread write concurrently (same reasoning as the
    metrics dump-dedup cell).

    Construction is on the per-request hot path (every wire request
    roots one of these inside the <1% epoch budget), so everything
    deferrable is deferred: the root span id and the span/link/anomaly
    containers materialize on first use, and the tail-sampling hash is
    cached from the raw id bytes at mint instead of re-parsing hex at
    finish.  Span RECORDS defer too — metrics spans carry their parent
    by reference and :func:`_resolve_span_ids` mints real ids only for
    traces the collector actually keeps."""

    __slots__ = (
        "trace_id", "_root_span_id", "remote_parent_id", "kind",
        "root_name", "request_id", "spans", "links", "anomalies",
        "device_ms", "_keep_frac",
    )

    def __init__(
        self,
        kind: str = "request",
        root_name: Optional[str] = None,
        request_id: Optional[str] = None,
        traceparent: Optional[str] = None,
    ):
        # None fast path: the common case (a locally-rooted trace)
        # must not pay the strict parser on every scope.
        parsed = (
            parse_traceparent(traceparent)
            if traceparent is not None else None
        )
        if parsed is not None:
            # Remote join: adopt the caller's trace id; our root span
            # parents under THEIR sending span.  The sampling hash is
            # computed lazily off the hex id if this segment finishes
            # healthy (keep_frac).
            self.trace_id, self.remote_parent_id = parsed
            self._keep_frac: Optional[float] = None
        else:
            # The high 64 bits ARE the sampling hash (hex chars 0..15),
            # so the keep fraction is cached straight off the integer —
            # no re-parse at finish.
            bits = _trace_rng.getrandbits(128)
            self.trace_id = format(bits, "032x")
            self.remote_parent_id = None
            self._keep_frac = (bits >> 64) / 2.0 ** 64
        self._root_span_id: Optional[str] = None
        self.kind = kind
        self.root_name = root_name or kind
        self.request_id = request_id
        self.spans: Optional[List[Dict[str, Any]]] = None
        self.links: Optional[List[Dict[str, Any]]] = None
        self.anomalies: Optional[set] = None
        self.device_ms = 0.0

    @property
    def root_span_id(self) -> str:
        """The root span's id, minted on first use (link sites, the
        outbound traceparent, and kept-trace payloads reach it; a
        dropped healthy trace never does)."""
        sid = self._root_span_id
        if sid is None:
            sid = self._root_span_id = mint_span_id()
        return sid

    def keep_frac(self) -> float:
        """The deterministic sampling hash (module docstring), cached.
        Matches :func:`keep_decision` exactly; a non-hex id (impossible
        for minted ids, parse-rejected for adopted ones) reads as 1.0 —
        never sampled in."""
        frac = self._keep_frac
        if frac is None:
            try:
                frac = int(self.trace_id[:16], 16) / 2.0 ** 64
            except ValueError:
                frac = 1.0
            self._keep_frac = frac
        return frac

    def mark(self, kind: str) -> None:
        anomalies = self.anomalies
        if anomalies is None:
            anomalies = self.anomalies = set()
        anomalies.add(kind)

    def link(self, trace_id: str, span_id: Optional[str] = None,
             relation: str = "") -> None:
        """Cross-trace edge (coalescer wave <-> submitting requests)."""
        entry: Dict[str, Any] = {"trace_id": trace_id}
        if span_id is not None:
            entry["span_id"] = span_id
        if relation:
            entry["relation"] = relation
        links = self.links
        if links is None:
            links = self.links = []
        links.append(entry)
        del links[: -_MAX_LINKS_PER_TRACE]

    def link_stream(self, stream_id: str) -> None:
        """Background traces (scrubber, snapshots) name the streams
        they touched — the operator pivot from a stream incident to the
        background activity around it."""
        links = self.links
        if links is None:
            links = self.links = []
        links.append({"stream_id": str(stream_id)})
        del links[: -_MAX_LINKS_PER_TRACE]

    def absorb(self, spans: List[Dict[str, Any]],
               device_ms: float = 0.0) -> None:
        """Fold one thread's completed spans (and its device time) in —
        called exactly once per scope teardown per thread."""
        if spans:
            mine = self.spans
            if mine is None:
                mine = self.spans = []
            mine.extend(spans)
            del mine[: -_MAX_SPANS_PER_TRACE]
        if device_ms:
            self.device_ms += device_ms

    def traceparent(self, span_id: Optional[str] = None) -> str:
        return format_traceparent(
            self.trace_id, span_id or self.root_span_id
        )


def _resolve_span_ids(state: TraceState) -> None:
    """Mint the real span ids for a KEPT trace's records — deferred
    from the hot path so a dropped trace never pays for id minting.
    Records carry their parent by REFERENCE (``_parent_rec``, attached
    at span enter); children exit (and so are listed) before their
    parents, so ids are assigned in one pass and parents resolved in a
    second.  A parent record that never completed (a watchdog worker's
    adoption point abandoned while still open) still gets an id minted
    onto it here, so :func:`join_trace` reports it as exactly the
    missing parent it is."""
    spans = state.spans
    if not spans:
        return
    root_id = state.root_span_id
    for rec in spans:
        if "span_id" not in rec:
            rec["span_id"] = mint_span_id()
    for rec in spans:
        parent = rec.pop("_parent_rec", None)
        if "parent_id" in rec:
            continue
        if parent is None:
            rec["parent_id"] = root_id
        else:
            sid = parent.get("span_id")
            if sid is None:
                sid = parent["span_id"] = mint_span_id()
            rec["parent_id"] = sid


# --- collector (tail sampler + ring + rotated dumps) ---------------------

class TraceCollector:
    """Tail-samples finished traces (module docstring).  ``finish`` is
    the single decision point: always-keep on any anomaly mark, else
    the deterministic ``sample_rate`` hash; kept traces enter a bounded
    ring, anomalous ones additionally rotate to ``dump_dir`` JSON under
    the flight-recorder disk discipline."""

    def __init__(
        self,
        capacity: int = 256,
        sample_rate: Optional[float] = None,
        latency_threshold_ms: Optional[float] = None,
        dump_dir: Optional[str] = None,
        keep_files: int = 64,
        disk_min_interval_s: float = 30.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        self.capacity = capacity
        if sample_rate is None:
            sample_rate = float(
                os.environ.get(ENV_TRACE_SAMPLE, "0.01")
            )
        self.sample_rate = sample_rate
        if latency_threshold_ms is None:
            raw = os.environ.get(ENV_TRACE_LATENCY_MS)
            latency_threshold_ms = float(raw) if raw else None
        self.latency_threshold_ms = latency_threshold_ms
        self.dump_dir = (
            dump_dir if dump_dir is not None
            else os.environ.get(ENV_TRACE_DIR)
        )
        self.keep_files = max(1, int(keep_files))
        self.disk_min_interval_s = disk_min_interval_s
        self._lock = threading.Lock()
        self._kept: List[Dict[str, Any]] = []
        self._counts = {
            "kept_anomalous": 0, "kept_sampled": 0, "dropped": 0,
        }
        self._dump_seq = 0
        self._last_disk_dump: Optional[float] = None
        self.last_anomalous_trace_id: Optional[str] = None
        # Per-outcome counter children, resolved once (the registry
        # lookup builds and hashes a label tuple — too heavy to pay on
        # every finish inside the <1% epoch budget).
        self._m_outcome: Dict[str, Any] = {}

    def fast_drop(self, state: TraceState) -> bool:
        """True = the trace was DROPPED and counted, and the caller may
        skip duration math, span absorption, and :meth:`finish`
        entirely.  A healthy trace's fate is sealed at mint (the
        sampling hash is deterministic), so the per-request teardown —
        the dominant outcome at production sample rates, priced inside
        the <1% epoch budget — pays only this decision and two counter
        bumps.  Bails to the full path whenever the outcome could still
        change: an anomaly already marked, a latency threshold armed
        (needs the duration), or a sampled-in hash."""
        if state.anomalies is not None or self.latency_threshold_ms is not None:
            return False
        frac = state._keep_frac
        if frac is None:
            frac = state.keep_frac()
        rate = self.sample_rate
        if rate >= 1.0 or frac < rate:
            return False
        ctr = self._m_outcome.get("dropped")
        if ctr is None:
            from . import metrics  # lazy: metrics imports this module

            ctr = self._m_outcome["dropped"] = metrics.REGISTRY.counter(
                "klba_trace_total", {"outcome": "dropped"}
            )
        ctr.inc()
        # GIL-relaxed increment, deliberately outside self._lock: two
        # request threads dropping in the same preemption window can
        # lose a count, at ~1e-4 odds, on the one stat where drift is
        # harmless (the registry counter above stays lock-exact, and
        # kept counts keep the locked path in finish).
        self._counts["dropped"] += 1
        return True

    def finish(
        self,
        state: TraceState,
        duration_ms: float,
        spans: Optional[List[Dict[str, Any]]] = None,
        device_ms: float = 0.0,
    ) -> str:
        """Close out one trace; returns the retention outcome
        (``kept_anomalous`` / ``kept_sampled`` / ``dropped``).

        ``spans``/``device_ms`` are the finishing thread's own tail,
        passed here instead of pre-absorbed so the DROPPED path skips
        the absorb (and the deferred span-id minting) entirely —
        decide first, pay only for kept traces."""
        if (
            self.latency_threshold_ms is not None
            and duration_ms > self.latency_threshold_ms
        ):
            state.mark("latency")
        if state.anomalies:
            outcome = "kept_anomalous"
        else:
            rate = self.sample_rate
            if rate >= 1.0 or (rate > 0.0 and state.keep_frac() < rate):
                outcome = "kept_sampled"
            else:
                outcome = "dropped"
        ctr = self._m_outcome.get(outcome)
        if ctr is None:
            from . import metrics  # lazy: metrics imports this module

            ctr = self._m_outcome[outcome] = metrics.REGISTRY.counter(
                "klba_trace_total", {"outcome": outcome}
            )
        ctr.inc()
        if outcome == "dropped":
            with self._lock:
                self._counts["dropped"] += 1
            return outcome
        from . import metrics  # lazy: metrics imports this module

        if spans or device_ms:
            state.absorb(spans or (), device_ms)
        _resolve_span_ids(state)
        trace = {
            "trace_id": state.trace_id,
            "kind": state.kind,
            "request_id": state.request_id,
            "outcome": outcome,
            "duration_ms": duration_ms,
            "root": {
                "name": state.root_name,
                "span_id": state.root_span_id,
                "parent_id": state.remote_parent_id,
                "start_ms": 0.0,
                "duration_ms": duration_ms,
                "device_ms": state.device_ms,
            },
            "spans": list(state.spans or ()),
            "links": list(state.links or ()),
            "anomalies": sorted(state.anomalies or ()),
        }
        write_file = False
        now = metrics.REGISTRY.clock()
        with self._lock:
            self._counts[outcome] += 1
            self._kept.append(trace)
            del self._kept[: -self.capacity]
            if outcome == "kept_anomalous":
                self.last_anomalous_trace_id = state.trace_id
                self._dump_seq += 1
                seq = self._dump_seq
                write_file = bool(self.dump_dir) and (
                    self._last_disk_dump is None
                    or now - self._last_disk_dump
                    >= self.disk_min_interval_s
                )
                if write_file:
                    self._last_disk_dump = now
        if write_file:
            self._write_dump(trace, seq)
        return outcome

    def _write_dump(self, trace: Dict[str, Any], seq: int) -> None:
        try:
            # Same durable-write rule as flight dumps: tmp + rename
            # (lint L015).  Imported lazily — utils/snapshot imports
            # utils/metrics which imports this module.
            from .snapshot import atomic_write_bytes

            path = os.path.join(
                self.dump_dir, f"trace-{seq % self.keep_files}.json"
            )
            # noqa: L017 below — a trace dump is post-mortem evidence,
            # never adoptable warm state: nothing reads it back, so
            # there is no fencing to police (same rationale as the
            # flight recorder's dumps).
            atomic_write_bytes(  # noqa: L017
                path,
                json.dumps(
                    trace, indent=2, sort_keys=True
                ).encode("utf-8"),
            )
        except OSError:
            LOGGER.warning(
                "trace dump to %s failed", self.dump_dir, exc_info=True
            )

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "kept_anomalous": self._counts["kept_anomalous"],
                "kept_sampled": self._counts["kept_sampled"],
                "dropped": self._counts["dropped"],
                "retained": len(self._kept),
                "sample_rate": self.sample_rate,
                "latency_threshold_ms": self.latency_threshold_ms,
                "last_anomalous_trace_id": self.last_anomalous_trace_id,
            }

    def traces(self, trace_id: Optional[str] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Kept traces, oldest first; a cross-process trace replayed
        in-process yields MULTIPLE entries for one id (one per scope)."""
        with self._lock:
            out = [
                t for t in self._kept
                if trace_id is None or t["trace_id"] == trace_id
            ]
        if limit is not None:
            out = out[-limit:] if limit > 0 else []
        return out

    def kept_ids(self) -> List[str]:
        with self._lock:
            return [t["trace_id"] for t in self._kept]

    def clear(self) -> None:
        """Drop retained traces + counters (test/bench bracketing)."""
        with self._lock:
            self._kept = []
            for k in self._counts:
                self._counts[k] = 0
            self.last_anomalous_trace_id = None


COLLECTOR = TraceCollector()


def collector() -> TraceCollector:
    return COLLECTOR


def mark(kind: str) -> None:
    """Stamp an anomaly on the calling thread's active trace (no-op
    outside a scope).  ``kind`` must be a registered
    :data:`ANOMALY_KINDS` member — an unknown kind is a programming
    error worth failing loudly in tests, but production marking sites
    run on serving paths, so it logs and drops instead of raising."""
    if kind not in ANOMALY_KINDS:
        LOGGER.warning("unknown trace anomaly kind %r dropped", kind)
        return
    from . import metrics  # lazy: metrics imports this module

    state = metrics.current_trace()
    if state is not None:
        state.mark(kind)


def mark_state(state: Optional[TraceState], kind: str) -> None:
    """Mark a trace by TOKEN — for anomaly sites running off-thread
    from the trace they indict (the coalescer flusher shedding a
    submitter's row)."""
    if state is None or kind not in ANOMALY_KINDS:
        return
    state.mark(kind)


# --- cross-process reconstruction ----------------------------------------

def join_trace(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Reconstruct ONE causal tree from every kept entry of a trace id
    (local + remote segments).  Returns a verdict dict the federated
    reconstruction test and ``bench.py config17_tracing`` both gate on:
    ``complete`` iff all entries share one id, exactly one segment is
    the origin (no remote parent), and every ``parent_id`` resolves
    within the union of spans."""
    ids = {e.get("trace_id") for e in entries}
    spans: Dict[str, Dict[str, Any]] = {}
    origins = []
    for e in entries:
        root = e.get("root") or {}
        if root.get("span_id"):
            spans[root["span_id"]] = root
        if root.get("parent_id") is None:
            origins.append(e)
        for s in e.get("spans", []):
            if s.get("span_id"):
                spans[s["span_id"]] = s
    missing = sorted({
        s["parent_id"] for s in spans.values()
        if s.get("parent_id") is not None
        and s["parent_id"] not in spans
    })
    return {
        "trace_id": next(iter(ids)) if len(ids) == 1 else None,
        "segments": len(entries),
        "origins": len(origins),
        "spans": len(spans),
        "missing_parents": missing,
        "complete": (
            len(entries) >= 1 and len(ids) == 1
            and len(origins) == 1 and not missing
        ),
    }
