"""Config and observability utilities."""
