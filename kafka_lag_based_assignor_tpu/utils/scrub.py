"""Resident-state scrubber: continuous integrity auditing, quarantine,
and bit-exact self-healing of long-lived device state.

Rounds 10-14 moved the whole steady state onto long-lived donated
device buffers — per-stream ``(choice, row_tab, counts, lags)`` and the
roster-locked megabatch's stacked batch — that survive for hours across
thousands of epochs.  Until this module the only integrity guard was
the delta path's lag-sum conservation check: a silently corrupted
choice or counts buffer on the dense path would serve invalid
assignments until churn happened to rebuild it.  Two complementary
defenses close that hole:

**Per-epoch fused digest** — every fused executable
(:mod:`..ops.streaming` / :mod:`..ops.coalesce`) additionally emits a
cheap device-computed ``int64[4]`` digest, fused into the dispatch the
epoch already pays (the FlashSinkhorn IO-aware argument: the dispatch
is upload/readback-bound, a few extra reductions are ~free):

====  ======================  =========================================
slot  value                   host truth it must match
====  ======================  =========================================
0     ``counts.sum()``        P — every partition owned exactly once
1     range violations        0 — no choice entry outside [-1, C)
2     ``lags.sum()``          the host lag sum (conservation law —
                              refine permutes ownership, never mass)
3     |bincount(choice) -     0 — the choice vector and the counts
      counts| L1 distance     buffer tell the same story
====  ======================  =========================================

The readback compares the digest against host truth on BOTH the
single-stream and locked-wave paths (:func:`digest_failures`); a
mismatch quarantines the stream/row.

**Background scrubber** — :class:`StateScrubber` round-robins idle
streams (and, through their handles, locked megabatch rows) on a
configurable cadence (``tpu.assignor.scrub.interval.ms``), OFF the
serving path: each pass is deadline-budgeted, skipped entirely while
the overload ladder is at rung >= 2 (an overloaded sidecar has no
spare device bandwidth for audits), and audits the full resident state
against the host mirror (:func:`audit_engine`): the device choice
buffer vs the engine's previous choice, the counts buffer vs its
bincount, the resident lag buffer vs the host lag mirror, and the row
table's segments vs the choice vector.

**Quarantine / self-heal** — a failed check (digest or audit) marks
the stream quarantined: the in-flight request is served via the
existing degraded ladder (``kept_previous`` or host snake — NEVER the
corrupt buffer; :class:`CorruptStateDetected` is a
:class:`..utils.watchdog.SolveRejected` subtype, so the service knows
the warm HOST state is intact and no breaker is charged), the resident
state is rebuilt bit-exact from host truth by the next dispatch
(exactly the ``seed_choice`` contract recovery replays — the host
previous-choice vector is the source of truth, the device state a
cache), megabatch rows evict-and-relock exactly once (one roster
invalidation, one re-stack wave), and REPEATED failures on one stream
escalate to the stream breaker
(:meth:`..utils.watchdog.Watchdog.trip_breaker` — a direct trip: the
healing epoch between strikes succeeds, so consecutive-failure
counting could never fire on exactly this pattern).

**Chaos surface** — fault points ``device.corrupt.choice`` /
``device.corrupt.counts`` / ``device.corrupt.lags`` /
``device.corrupt.row_tab`` inject seeded
bit-flips into the resident buffers at readback boundaries
(:func:`corruption_plan` / :func:`flip_bit`), so the whole plane is
drill-testable: the ``corruption_storm`` bench probe gates detection
latency, bit-exact healing, and zero invalid served assignments.

Telemetry: ``klba_scrub_passes_total``,
``klba_scrub_streams_audited_total``,
``klba_scrub_failures_total{buffer}``,
``klba_scrub_skipped_total{reason}``, ``klba_scrub_duration_ms``,
``klba_quarantine_total{buffer,outcome}`` (outcome = ``quarantined`` |
``healed`` | ``resynced`` | ``escalated``), and ``scrub`` /
``quarantine`` flight records.  See DEPLOYMENT.md "State integrity".
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import faults, metrics
from . import trace as trace_mod
from .watchdog import SolveRejected

LOGGER = logging.getLogger(__name__)

#: The digest vector's base length (int64[4]; see the module
#: docstring).  Fused epilogues that also audit the [C, M] row table
#: append a fifth lane (``ops.refine._row_tab_lane_xla``, host truth
#: 0) — :func:`digest_failures` accepts both shapes.
DIGEST_LEN = 4

#: The corrupted-buffer fault points, by buffer class.
CORRUPT_POINTS = {
    "choice": "device.corrupt.choice",
    "counts": "device.corrupt.counts",
    "lags": "device.corrupt.lags",
    "row_tab": "device.corrupt.row_tab",
}

#: Quarantine outcomes (the ``klba_quarantine_total`` label values).
QUARANTINE_OUTCOMES = ("quarantined", "healed", "resynced", "escalated")

#: Quarantine strikes on ONE stream before each further failure is
#: also charged to the stream breaker (utils/watchdog.trip_breaker):
#: a single cosmic-ray flip heals silently, a device that keeps
#: corrupting state is as dead as one that keeps raising.
ESCALATE_AFTER = 2

#: Consecutive CLEAN served epochs that forgive a stream's strikes.
#: Deliberately more than one: a corrupt -> heal -> corrupt flip-flop
#: serves a clean healing epoch between every detection, and resetting
#: on each of those would make the repeating pattern — exactly the
#: failing-hardware signature escalation exists for — never escalate.
FORGIVE_AFTER = 3


class CorruptStateDetected(SolveRejected):
    """A resident-state integrity check failed: the dispatch's output
    (or the audited device state) does not match host truth, so the
    answer must NOT be served.  Subtypes :class:`SolveRejected`
    deliberately — by the time this raises the engine has already
    QUARANTINED itself (resident dropped, host previous-choice intact),
    so the service's fail-fast handler serves ``kept_previous`` (or the
    host snake) and no breaker is charged; the next epoch rebuilds the
    device state bit-exact from host truth.  ``buffers`` names the
    buffer classes that failed (``choice`` / ``counts`` / ``lags`` /
    ``row_tab``)."""

    def __init__(self, message: str, buffers: Sequence[str]):
        super().__init__(message)
        self.buffers = list(buffers)


def digest_failures(
    digest: Any, expected_p: int, expected_lag_sum: Optional[int]
) -> List[str]:
    """Compare a dispatch's device digest against host truth; returns
    the failed buffer classes (empty = clean).  ``expected_lag_sum``
    None skips the lag-checksum slot (callers without a host sum)."""
    d = np.asarray(digest)
    fails: List[str] = []
    if int(d[0]) != int(expected_p):
        fails.append("counts")
    if int(d[1]) != 0 or int(d[3]) != 0:
        fails.append("choice")
    if expected_lag_sum is not None and int(d[2]) != int(expected_lag_sum):
        fails.append("lags")
    # The optional fifth lane: the row TABLE's slot-level checksum
    # (ops/refine._row_tab_lane_xla — host truth 0).  Digests from
    # epilogues predating (or not holding) a table stay int64[4].
    if d.shape[0] > DIGEST_LEN and int(d[DIGEST_LEN]) != 0:
        fails.append("row_tab")
    return fails


def record_quarantine(
    buffers: Sequence[str],
    outcome: str,
    stream_id: Optional[str] = None,
    source: Optional[str] = None,
) -> None:
    """Account one quarantine-plane event with ONE schema no matter
    which layer detected it (per-epoch digest, scrubber audit, or the
    coalescer's row check): ``klba_quarantine_total{buffer,outcome}``
    plus a ``quarantine`` flight record and a ``quarantine`` anomaly
    mark on the active trace (quarantines are always-keep for the tail
    sampler, whichever scope — request, scrub pass, or coalescer wave —
    detected them).  Runs only on failure/heal paths, so the registry's
    own get-or-create is plenty."""
    trace_mod.mark("quarantine")
    for buffer in buffers:
        metrics.REGISTRY.counter(
            "klba_quarantine_total",
            {"buffer": buffer, "outcome": outcome},
        ).inc()
    metrics.FLIGHT.record(
        "quarantine",
        {
            "buffers": list(buffers),
            "outcome": outcome,
            "stream_id": stream_id,
            "source": source,
        },
    )


# -- chaos: seeded bit-flip injection -------------------------------------


def corruption_plan(limit: Optional[int] = None) -> List[Tuple[str, int]]:
    """Consult the three ``device.corrupt.*`` fault points; returns
    ``[(buffer, seed), ...]`` for each point whose plan fires at this
    call site (empty when no injector is active — the steady state pays
    one global load per point).  The seed is derived from the
    injector's own seed and the point's call count, so the same drill
    schedule replays the same flips.  ``limit`` is folded in so two
    sites with different bounds still diverge deterministically."""
    inj = faults.active()
    if inj is None:
        return []
    plan: List[Tuple[str, int]] = []
    for buffer, point in CORRUPT_POINTS.items():
        try:
            faults.fire(point)
        except faults.FaultError:
            seed = (
                inj.seed * 1_000_003
                + inj.calls(point) * 97
                + (int(limit) if limit else 0)
            )
            plan.append((buffer, seed))
    return plan


def flip_bit(arr: np.ndarray, seed: int, limit: Optional[int] = None):
    """One seeded single-bit flip in ``arr`` (a host copy is returned;
    the caller re-uploads it).  ``limit`` bounds the flipped index to
    the REAL (un-padded) prefix — corruption of padding is harmless by
    construction, so drills flip where it matters."""
    rng = np.random.default_rng(seed)
    out = np.array(arr, copy=True)
    flat = out.reshape(-1)
    hi = flat.size if limit is None else min(int(limit), flat.size)
    i = int(rng.integers(max(hi, 1)))
    bit = int(rng.integers(8 * out.dtype.itemsize - 1))
    flat[i] = np.bitwise_xor(
        flat[i], out.dtype.type(np.int64(1) << bit)
    )
    return out


# -- the host-truth audit -------------------------------------------------


def audit_engine(engine) -> Tuple[bool, List[str]]:
    """Audit one streaming engine's FULL resident state against its
    host mirror; returns ``(audited, failed_buffers)``.

    ``audited`` False means there was nothing to check (cold engine,
    stale resident, host state mid-repair) — not a pass.  The caller
    must hold whatever lock serializes the engine against concurrent
    epochs (the sidecar audits under the stream lock, idle streams
    only).  A locked-roster handle materializes its row (one gather per
    buffer — the ``coalesce.gather`` fault point fires there, so drills
    exercise this path too)."""
    prev = getattr(engine, "_prev_choice", None)
    resident = getattr(engine, "_resident", None)
    if prev is None or resident is None:
        return False, []
    C = int(engine.num_consumers)
    P = int(prev.shape[0])
    if P == 0 or int(prev.min()) < 0 or int(prev.max()) >= C:
        # Host state mid-repair (orphans) — the resident is stale or
        # about to be dropped; nothing trustworthy to diff against.
        return False, []
    materialize = getattr(resident, "materialize", None)
    bufs = materialize() if materialize is not None else resident
    choice_d = np.asarray(bufs[0])
    row_tab = np.asarray(bufs[1])
    counts_d = np.asarray(bufs[2])
    lags_d = np.asarray(bufs[3])
    fails: List[str] = []
    if choice_d.shape[0] < P or not np.array_equal(choice_d[:P], prev):
        fails.append("choice")
    expected_counts = np.bincount(prev, minlength=C).astype(counts_d.dtype)
    if not np.array_equal(counts_d, expected_counts):
        fails.append("counts")
    mirror = getattr(engine, "_lag_mirror", None)
    if mirror is not None and (
        lags_d.shape[0] < P
        or not np.array_equal(lags_d[:P], mirror.astype(lags_d.dtype))
    ):
        fails.append("lags")
    # Row table: every consumer's occupied slots must name rows the
    # host choice actually assigns to that consumer (the table is what
    # the fused totals derivation gathers through — a corrupt segment
    # silently mis-weights the quality loop).
    M = row_tab.shape[1]
    slot_ok = np.arange(M)[None, :] < expected_counts[:, None]
    rows = row_tab[slot_ok]
    owners = np.repeat(np.arange(C), expected_counts.clip(max=M))
    if (
        rows.size != owners.size
        or np.any(rows < 0)
        or np.any(rows >= P)
        or not np.array_equal(prev[rows], owners)
    ):
        fails.append("row_tab")
    return True, fails


# -- the background scrubber ----------------------------------------------


class StateScrubber:
    """Round-robin background auditor (module docstring).

    ``targets`` returns the current audit jobs as ``(stream_id,
    auditor)`` pairs; each ``auditor()`` performs ONE audit attempt and
    returns ``"audited"`` | ``"busy"`` (lock contended / not idle) |
    ``"skipped"`` (nothing to audit) — the auditor owns locking and
    quarantine handling, so this class stays free of engine imports.
    ``suppress`` True skips the whole pass (the sidecar wires the
    overload ladder's rung >= 2 here).  Each pass walks at most one
    full rotation and stops early when ``budget_s`` is spent — the
    scrubber must never become the load it is auditing for."""

    def __init__(
        self,
        targets: Callable[[], List[Tuple[str, Callable[[], str]]]],
        interval_s: float,
        budget_s: float = 0.25,
        suppress: Optional[Callable[[], bool]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        if budget_s <= 0:
            raise ValueError(f"budget_s={budget_s} must be > 0")
        self._targets = targets
        self.interval_s = float(interval_s)
        self.budget_s = float(budget_s)
        self._suppress = suppress or (lambda: False)
        self._clock = clock or metrics.REGISTRY.clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cursor = 0
        self.last_pass_at: Optional[float] = None
        # Scrub-coverage SLO (ROADMAP state-integrity (b)): the last
        # instant the scrubber made PROGRESS — audited at least one
        # stream, or legitimately had nothing to audit.  A pass that
        # only hit busy locks (or was suppressed/crashed) does not
        # count: ``stalled`` flips once progress is older than
        # ``stall_after_s`` (3 intervals — one slow pass is noise,
        # three is a wedge), so a wedged scrubber is visible by
        # PRESENCE (a flag + the klba_scrub_last_pass_age_s gauge),
        # not by the absence of audit counters.
        self._started_at = (clock or metrics.REGISTRY.clock)()
        self.last_progress_at = self._started_at
        self.stall_after_s = 3.0 * float(interval_s)
        self._m_last_age = metrics.REGISTRY.gauge(
            "klba_scrub_last_pass_age_s"
        )
        self._m_passes = metrics.REGISTRY.counter("klba_scrub_passes_total")
        self._m_audited = metrics.REGISTRY.counter(
            "klba_scrub_streams_audited_total"
        )
        # Construction baselines: the registry series are process-wide
        # (two services per process is routine in tests and drills),
        # so the per-instance stats() view reports deltas — the same
        # policy as the service's requests/errors counters.
        self._base_passes = self._m_passes.value
        self._base_audited = self._m_audited.value
        self._m_skipped = {
            r: metrics.REGISTRY.counter(
                "klba_scrub_skipped_total", {"reason": r}
            )
            for r in ("overload", "busy", "error")
        }
        self._m_duration = metrics.REGISTRY.histogram(
            "klba_scrub_duration_ms"
        )

    def start(self) -> "StateScrubber":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="klba-scrub", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrub_once()
            except Exception:  # noqa: BLE001 — the auditor must survive
                LOGGER.warning("scrub pass crashed", exc_info=True)
                self._m_skipped["error"].inc()

    def scrub_once(self) -> Dict[str, int]:
        """One deadline-budgeted pass (also the drill/test entry point);
        returns ``{audited, busy, suppressed}`` counts.  Runs as a
        self-rooted ``background`` trace (root ``scrub.pass``) linked
        to every stream it audits — a quarantine found here marks the
        pass anomalous, so tail sampling keeps it.  An outer scope, if
        already active (a drill inside a request), wins instead."""
        if self._suppress():
            # Overload rung >= 2: the device has no spare bandwidth for
            # audits — integrity resumes when the ladder steps down.
            self._m_skipped["overload"].inc()
            return {"audited": 0, "busy": 0, "suppressed": 1}
        with metrics.request_scope(
            kind="background", root_name="scrub.pass"
        ):
            return self._scrub_pass()

    def _scrub_pass(self) -> Dict[str, int]:
        started = self._clock()
        deadline = started + self.budget_s
        jobs = self._targets()
        audited = busy = attempted = 0
        n = len(jobs)
        for k in range(n):
            if self._clock() >= deadline:
                break
            sid, auditor = jobs[(self._cursor + k) % n]
            attempted += 1
            try:
                outcome = auditor()
            except Exception:  # noqa: BLE001 — one bad audit, not the pass
                LOGGER.warning(
                    "scrub audit of stream %r failed", sid, exc_info=True
                )
                self._m_skipped["error"].inc()
                continue
            if outcome == "audited":
                audited += 1
                self._m_audited.inc()
                tr = metrics.current_trace()
                if tr is not None:
                    tr.link_stream(sid)
            elif outcome == "busy":
                busy += 1
                self._m_skipped["busy"].inc()
        if n:
            # Round-robin: the next pass resumes where the budget cut
            # this one off, so a large fleet still gets full coverage
            # across passes instead of re-auditing the same prefix.
            self._cursor = (self._cursor + attempted) % n
        self.last_pass_at = self._clock()
        if audited > 0 or n == 0:
            # Progress for the coverage SLO: streams were audited, or
            # there was genuinely nothing to audit (an idle sidecar is
            # not a wedged scrubber).
            self.last_progress_at = self.last_pass_at
        self._m_passes.inc()
        self._m_duration.observe((self.last_pass_at - started) * 1000.0)
        metrics.FLIGHT.record(
            "scrub", {"targets": n, "audited": audited, "busy": busy}
        )
        return {"audited": audited, "busy": busy, "suppressed": 0}

    def stats(self) -> Dict[str, Any]:
        """The operator surface (wire ``stats.scrub`` /
        tools/dump_metrics.py --summary).  Reading it refreshes the
        ``klba_scrub_last_pass_age_s`` gauge (age is a pull-time
        quantity), and ``stalled`` is the coverage-SLO flag: no audit
        progress for > 3 intervals — the CALLER (service.scrub_stats)
        combines it with "streams are live" into ``wedged``."""
        now = self._clock()
        last = self.last_pass_at
        age = now - (last if last is not None else self._started_at)
        self._m_last_age.set(age)
        return {
            "interval_ms": self.interval_s * 1000.0,
            "last_pass_age_s": age,
            "stalled": (
                now - self.last_progress_at > self.stall_after_s
            ),
            "passes": self._m_passes.value - self._base_passes,
            "streams_audited": (
                self._m_audited.value - self._base_audited
            ),
        }
