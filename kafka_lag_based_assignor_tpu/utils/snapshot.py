"""Crash-safe lifecycle snapshots: the sidecar's warm state, durable.

Rounds 7 and 11 hardened the service against *external* failures, but
every byte of warm state — per-stream choices and rosters, SLO classes,
the recommend call's lag-trend windows, breaker cooldowns, the overload
rung — lived only in process memory.  A deploy or crash therefore
cold-started ALL tenants at once: the self-inflicted stampede the
round-11 shed ladder exists to survive, and a blackout for the
elasticity loop (the lag history an external autoscaler projects from,
arXiv:2402.06085).  This module makes restarts a non-event: the
service periodically (and on churn) snapshots its host-recoverable
state, and a restarting process rehydrates from it off the serving
path (see service.py's recovery and DEPLOYMENT.md "Restarts and
recovery").

Format (one JSON document)::

    {"format": "klba-snapshot", "version": 1, "written_at": <unix s>,
     "sections": {"streams":  {"crc32": <int>, "body": {...}},
                  "breakers": {"crc32": <int>, "body": {...}},
                  "overload": {"crc32": <int>, "body": {...}}}}

Design rules, in failure-model order:

* **Atomic**: a snapshot is written to a same-directory temp file and
  ``os.rename``-d into place (:func:`atomic_write_bytes` — THE helper
  every durable package write must go through, lint rule L015), so a
  crash mid-write leaves the previous snapshot intact and a reader can
  never observe a torn file from this writer.
* **Versioned**: a loader only trusts ``version == SNAPSHOT_VERSION``.
  A WRONG version (older writer) and a FUTURE version (newer writer, a
  rolled-back deploy) both load as a counted cold start — never a
  guess at a foreign schema.
* **Per-section checksummed**: each section's body carries a CRC32 of
  its canonical JSON encoding.  A corrupt section (bit rot, a torn
  copy) is SKIPPED and counted — the other sections still load; losing
  the breaker states must not cost every tenant its warm roster.
* **Fail-open**: :meth:`SnapshotStore.load` never raises into the
  serving path.  Anything unreadable — missing file, truncated JSON,
  wrong format marker — is a counted cold start; anything partially
  readable is a counted partial load.  :meth:`SnapshotStore.save`
  never raises either (an outage of the snapshot volume must not take
  the sidecar down); failures land in
  ``klba_snapshot_writes_total{outcome="error"}``.

Backends and cross-host hand-off (ISSUE 9; DEPLOYMENT.md "Restarts
and recovery"): the store persists through a pluggable
:class:`SnapshotBackend`.  ``file`` is the round-12 per-instance
atomic local file; ``memory`` and ``object`` are object-store-shaped
backends (an in-memory cell shared by path, and a filesystem-simulated
object store) that speak the full remote protocol — **versioned
compare-and-swap** writes plus **epoch-fenced writer leases**:

* every object write can be conditioned on the object version last
  observed (``write_if(data, prev_version=...)`` — a mismatch raises
  :class:`CASConflict`, the loser never lands);
* a writer first acquires a **lease** whose fencing ``token`` is
  minted by CAS and monotone across acquisitions: a replacement
  instance that takes over (lease expired or released) holds a HIGHER
  token, and every subsequent write from the fenced-off predecessor —
  its ``write_if`` carries its stale token — raises
  :class:`FencedWriter` and is rejected loudly (counted as
  ``klba_snapshot_writes_total{outcome="fenced"}``, flight-recorded)
  instead of clobbering the adopted state.

Lease semantics: ``acquire_lease`` succeeds only when no LIVE lease is
held by another owner (else :class:`LeaseHeld`); a successful acquire
always bumps the token (a fresh fencing epoch).  ``renew_lease``
extends the expiry WITHOUT changing the token; an expired-but-
unsuperseded lease may still write (and renews on the next save) — the
token, not the clock, is the authority, exactly like object-store
generation numbers.  All of this stays fail-open at the store level: a
backend outage (fault point ``backend.partition``) never takes
assignment down — saves count errors, loads count cold starts, and a
boot that cannot acquire the lease serves anyway with writes denied
(``outcome="no_lease"``).

Fault points (utils/faults, wired into the chaos suite):
``snapshot.write`` fires at the head of every save, ``snapshot.load``
at the head of every load — both exercise the fail-open contracts
above.  ``backend.partition`` / ``backend.latency`` fire at the head
of every backend operation (an unreachable / slow remote store);
``snapshot.cas`` fires inside conditional writes (a simulated CAS
race — the write loses as :class:`CASConflict`); ``snapshot.lease``
fires inside lease acquire/renew/release (a lease-channel failure).

Telemetry: ``klba_snapshot_writes_total{outcome}`` (``ok`` | ``error``
| ``fenced`` | ``no_lease``), ``klba_snapshot_write_duration_ms``,
``klba_snapshot_bytes``, ``klba_snapshot_loads_total{outcome}``,
``klba_snapshot_sections_skipped_total{section}``,
``klba_snapshot_cas_conflicts_total``,
``klba_lease_acquires_total{outcome}``,
``klba_lease_releases_total``,
``klba_lease_takeovers_total{previous}``.

Clock discipline: durations flow through the registry clock (L012);
``written_at`` / snapshot age need a WALL clock that survives a
process restart, so the store takes an injectable ``wall_clock``
defaulting to ``time.time`` (referenced, never called directly).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import faults, metrics

LOGGER = logging.getLogger(__name__)

#: The schema version THIS writer produces and the only one the loader
#: trusts.  Bump it on any incompatible body change; the rollout story
#: (DEPLOYMENT.md "Restarts and recovery") is that a version mismatch
#: is a clean cold start, never a migration attempt in the sidecar.
SNAPSHOT_VERSION = 1

_FORMAT = "klba-snapshot"

#: Load outcomes, the ``klba_snapshot_loads_total`` label values:
#: ``ok`` (every section verified), ``partial`` (>= 1 section skipped),
#: ``cold`` (nothing usable: corrupt/wrong-version/unreadable),
#: ``missing`` (no file — the normal first boot).
LOAD_OUTCOMES = ("ok", "partial", "cold", "missing")


def _canonical(body: Any) -> bytes:
    """THE byte encoding the section checksums are computed over —
    shared by save and load so the two can never disagree on
    whitespace or key order."""
    return json.dumps(
        body, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")


def section_crc(body: Any) -> int:
    """CRC32 of a section body's canonical encoding (exposed so tests
    can build hand-tampered snapshots)."""
    return zlib.crc32(_canonical(body))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """THE durable-write helper (lint rule L015): write ``data`` to a
    same-directory temp file, fsync, then ``os.rename`` over ``path``.
    A reader can observe the old file or the new file, never a torn
    mix; a crash mid-write leaves the old file untouched.  The temp
    name carries the pid so two processes pointed at one path cannot
    corrupt each other's staging (last rename still wins, atomically).
    """
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        # Never leave staging litter next to the real file; the rename
        # either happened (tmp is gone) or the write is abandoned.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# -- snapshot backends (cross-host hand-off) -------------------------------

#: Backend kinds ``build_backend`` (and the service/config layer)
#: accepts.  ``file`` = the per-instance atomic local file (round 12);
#: ``memory`` = an in-process cell shared by path (tests, drills, and
#: the two-instance soaks); ``object`` = a filesystem-simulated object
#: store (a directory of versioned objects + a meta/lease document) —
#: the full remote CAS + lease protocol, tier-1-testable.
BACKEND_KINDS = ("file", "memory", "object")


class CASConflict(RuntimeError):
    """A conditional write lost its compare-and-swap: the object
    version moved under the writer.  The loser's data never landed."""


class FencedWriter(RuntimeError):
    """A write (or renew) carried a STALE fencing token: a replacement
    instance holds a newer lease.  The write was rejected; the caller
    must stop writing — its warm-state epoch is over."""


class LeaseHeld(RuntimeError):
    """``acquire_lease`` found a live lease held by another owner."""

    def __init__(self, owner: str, expires_in_s: float):
        super().__init__(
            f"writer lease held by {owner!r} for another "
            f"{expires_in_s:.3f}s"
        )
        self.owner = owner
        self.expires_in_s = expires_in_s


class Lease:
    """One granted writer lease: the monotone fencing ``token`` is the
    write authority; ``expires_at`` / ``acquired_at`` are wall-clock
    (they must be comparable across hosts and restarts)."""

    __slots__ = ("owner", "token", "expires_at", "acquired_at")

    def __init__(
        self, owner: str, token: int, expires_at: float,
        acquired_at: float,
    ):
        self.owner = owner
        self.token = int(token)
        self.expires_at = float(expires_at)
        self.acquired_at = float(acquired_at)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "owner": self.owner,
            "token": self.token,
            "expires_at": self.expires_at,
            "acquired_at": self.acquired_at,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Lease":
        return cls(
            str(d["owner"]), int(d["token"]), float(d["expires_at"]),
            float(d.get("acquired_at", 0.0)),
        )


def _lease_live(lease: Optional[Dict[str, Any]], now: float) -> bool:
    return lease is not None and float(lease["expires_at"]) > now


class SnapshotBackend:
    """Abstract snapshot persistence: versioned objects + writer
    leases.  Subclasses implement the six primitives under their own
    mutual exclusion; the CAS/fencing *semantics* live here so the
    three backends cannot diverge.

    State model per backend: one object (the snapshot document bytes)
    with a monotone ``object_version`` (0 = never written), plus an
    optional lease record ``{owner, token, expires_at, acquired_at}``
    and a ``fence_token`` — the highest token EVER minted, persisted
    independently of the lease so a release can never reset the
    fencing epoch (a stale holder's token must stay stale forever; the
    ``released`` record additionally remembers who handed off, for the
    lifecycle surface).  Every public operation fires the shared fault
    points (``backend.latency`` then ``backend.partition``); lease
    operations additionally fire ``snapshot.lease`` and conditional
    writes ``snapshot.cas``.
    """

    kind = "abstract"

    def __init__(self, wall_clock: Callable[[], float] = time.time):
        self._wall = wall_clock

    # -- primitives (subclass responsibility, caller-locked) ---------------

    def _load_state(self) -> Dict[str, Any]:
        """Normalized state dict (see :meth:`_norm_state`)."""
        raise NotImplementedError

    @staticmethod
    def _norm_state(raw: Dict[str, Any]) -> Dict[str, Any]:
        """Normalize a raw persisted state document: defaults, copies,
        and the fence-token backfill (documents written before a
        release carry the token only inside the lease)."""
        lease = raw.get("lease")
        released = raw.get("released")
        fence = raw.get("fence_token")
        if fence is None:
            fence = int(lease["token"]) if lease else 0
        return {
            "object_version": int(raw.get("object_version", 0)),
            "lease": dict(lease) if lease else None,
            "released": dict(released) if released else None,
            "fence_token": int(fence),
        }

    def _store_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def _read_data(self, state: Dict[str, Any]) -> Optional[bytes]:
        raise NotImplementedError

    def _write_data(self, data: bytes, new_version: int) -> None:
        raise NotImplementedError

    def _mutex(self):
        """Context manager serializing read-modify-write cycles."""
        raise NotImplementedError

    def describe(self) -> str:
        raise NotImplementedError

    # -- shared fault hooks ------------------------------------------------

    def _enter(self) -> None:
        """Every backend op passes here: ``backend.latency`` (sleep,
        then proceed) models a slow link, ``backend.partition``
        (raise) an unreachable store."""
        faults.fire("backend.latency")
        faults.fire("backend.partition")

    # -- object ops --------------------------------------------------------

    def read(self) -> Tuple[Optional[bytes], int]:
        """``(data, object_version)``; ``(None, v)`` when no object is
        readable.  Lease-free — recovery may always LOOK."""
        self._enter()
        with self._mutex():
            state = self._load_state()
            return self._read_data(state), int(state["object_version"])

    def version(self) -> int:
        self._enter()
        with self._mutex():
            return int(self._load_state()["object_version"])

    def write_if(
        self,
        data: bytes,
        prev_version: Optional[int] = None,
        token: Optional[int] = None,
    ) -> int:
        """Write the object; returns the new version.

        ``prev_version`` (when not None) must equal the current object
        version or :class:`CASConflict` is raised — the loser never
        lands.  ``token`` (when not None) must equal the CURRENT lease
        token or :class:`FencedWriter` is raised — a fenced-off
        predecessor can never clobber its replacement's adopted state,
        even with a "winning" version guess.  Both None = the
        unconditional legacy write (round-12 semantics)."""
        self._enter()
        if prev_version is not None or token is not None:
            try:
                faults.fire("snapshot.cas")
            except faults.FaultError as exc:
                # The injected CAS race: this write LOSES, exactly as
                # if a concurrent writer bumped the version first.
                raise CASConflict(f"injected CAS race: {exc}") from exc
        with self._mutex():
            state = self._load_state()
            if token is not None:
                lease = state.get("lease")
                if lease is None or int(lease["token"]) != int(token):
                    raise FencedWriter(
                        f"write with fencing token {token} rejected: "
                        f"current lease is "
                        f"{lease and lease.get('token')!r} "
                        f"(held by {lease and lease.get('owner')!r})"
                    )
            if prev_version is not None and (
                int(prev_version) != int(state["object_version"])
            ):
                raise CASConflict(
                    f"object version moved: expected {prev_version}, "
                    f"backend holds {state['object_version']}"
                )
            new_version = int(state["object_version"]) + 1
            self._write_data(data, new_version)
            state["object_version"] = new_version
            self._store_state(state)
            return new_version

    # -- lease ops ---------------------------------------------------------

    def read_lease(self) -> Optional[Lease]:
        self._enter()
        with self._mutex():
            lease = self._load_state().get("lease")
            return Lease.from_dict(lease) if lease else None

    def lease_state(self) -> Dict[str, Any]:
        """Raw lease-channel state ``{lease, released, fence_token}``
        — the hand-off observability read (who held the state before
        this boot, and whether they crashed or drained)."""
        self._enter()
        with self._mutex():
            state = self._load_state()
            return {
                "lease": state.get("lease"),
                "released": state.get("released"),
                "fence_token": int(state.get("fence_token", 0)),
            }

    def acquire_lease(self, owner: str, ttl_s: float) -> Lease:
        """Grant (token = highest ever minted + 1) unless a LIVE lease
        is held by another owner (:class:`LeaseHeld`).  An expired or
        released lease is taken over — the MONOTONE token bump is what
        fences the previous holder out, and it survives releases (the
        ``fence_token``), so a drained predecessor's stale token can
        never collide with a successor's."""
        self._enter()
        faults.fire("snapshot.lease")
        now = self._wall()
        with self._mutex():
            state = self._load_state()
            cur = state.get("lease")
            if _lease_live(cur, now) and cur["owner"] != owner:
                raise LeaseHeld(
                    str(cur["owner"]), float(cur["expires_at"]) - now
                )
            token = max(
                int(state.get("fence_token", 0)),
                int(cur["token"]) if cur else 0,
            ) + 1
            lease = Lease(owner, token, now + float(ttl_s), now)
            state["lease"] = lease.as_dict()
            state["fence_token"] = token
            state["released"] = None
            self._store_state(state)
            return lease

    def renew_lease(self, lease: Lease, ttl_s: float) -> Lease:
        """Extend the expiry of the lease named by ``lease.token``
        (token unchanged); :class:`FencedWriter` when superseded."""
        self._enter()
        faults.fire("snapshot.lease")
        now = self._wall()
        with self._mutex():
            state = self._load_state()
            cur = state.get("lease")
            if cur is None or int(cur["token"]) != lease.token:
                raise FencedWriter(
                    f"renew with token {lease.token} rejected: current "
                    f"lease is {cur and cur.get('token')!r}"
                )
            renewed = Lease(
                lease.owner, lease.token, now + float(ttl_s),
                float(cur.get("acquired_at", now)),
            )
            state["lease"] = renewed.as_dict()
            self._store_state(state)
            return renewed

    def release_lease(self, lease: Lease) -> None:
        """Drop the lease iff still ours (a superseded release is a
        no-op — never yank the replacement's lease)."""
        self._enter()
        faults.fire("snapshot.lease")
        with self._mutex():
            state = self._load_state()
            cur = state.get("lease")
            if cur is not None and int(cur["token"]) == lease.token:
                state["released"] = cur
                state["lease"] = None
                self._store_state(state)


#: In-memory backend cells, shared BY PATH within the process: two
#: service instances constructed with the same path (a restart drill,
#: the two-instance soaks) see one "remote" store.  Plain dict under
#: the module import lock semantics; each cell carries its own lock.
_MEMORY_CELLS: Dict[str, Dict[str, Any]] = {}
_MEMORY_CELLS_LOCK = threading.Lock()


def reset_memory_backends() -> None:
    """Drop every in-memory cell (test hygiene)."""
    with _MEMORY_CELLS_LOCK:
        _MEMORY_CELLS.clear()


class InMemoryBackend(SnapshotBackend):
    """Object-store-shaped backend in process memory, keyed by name:
    the CAS + lease protocol with zero I/O — what the failure-matrix
    tests and the concurrent-writer soaks run against."""

    kind = "memory"

    def __init__(
        self, name: str, wall_clock: Callable[[], float] = time.time
    ):
        super().__init__(wall_clock)
        self.name = str(name)
        with _MEMORY_CELLS_LOCK:
            cell = _MEMORY_CELLS.get(self.name)
            if cell is None:
                cell = _MEMORY_CELLS[self.name] = {
                    "lock": threading.RLock(),
                    "state": self._norm_state({}),
                    "data": None,
                }
        self._cell = cell

    def _mutex(self):
        return self._cell["lock"]

    def _load_state(self) -> Dict[str, Any]:
        # Copy: callers mutate the dict before _store_state.
        return self._norm_state(self._cell["state"])

    def _store_state(self, state: Dict[str, Any]) -> None:
        self._cell["state"] = self._norm_state(state)

    def _read_data(self, state: Dict[str, Any]) -> Optional[bytes]:
        return self._cell["data"]

    def _write_data(self, data: bytes, new_version: int) -> None:
        self._cell["data"] = bytes(data)

    def describe(self) -> str:
        return f"memory://{self.name}"


class _FsMutex:
    """O_CREAT|O_EXCL lock-file mutex for the filesystem backends'
    read-modify-write cycles: held only for the (sub-ms) meta RMW, a
    stale lock (holder crashed mid-cycle) is broken after
    ``stale_s``.

    Ownership-safe: the lock file carries a unique owner token.
    Breaking a stale lock RENAMES it first (atomic — exactly one
    breaker wins, and a resumed holder can no longer be holding the
    live path), and release verifies the token before unlinking, so a
    holder that stalled past ``stale_s`` and resumed can never delete
    its successor's live lock."""

    _SEQ = iter(range(1, 1 << 30))

    def __init__(
        self,
        path: str,
        wall_clock: Callable[[], float],
        timeout_s: float = 5.0,
        stale_s: float = 5.0,
    ):
        self.path = path
        self._wall = wall_clock
        self.timeout_s = float(timeout_s)
        self.stale_s = float(stale_s)
        self._token = f"{os.getpid()}.{next(self._SEQ)}"

    def __enter__(self) -> "_FsMutex":
        deadline = self._wall() + self.timeout_s
        while True:
            try:
                fd = os.open(
                    self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
                os.write(fd, self._token.encode())
                os.close(fd)
                return self
            except FileExistsError:
                try:
                    age = self._wall() - os.path.getmtime(self.path)
                    if age > self.stale_s:
                        # Break by RENAME, not unlink-in-place: the
                        # rename is atomic, so exactly one breaker
                        # claims the stale lock and a resumed stale
                        # holder finds its file gone instead of
                        # racing the successor's.
                        doomed = f"{self.path}.stale.{self._token}"
                        os.rename(self.path, doomed)
                        os.unlink(doomed)
                        continue
                except OSError:
                    continue  # holder released between stat and break
                if self._wall() >= deadline:
                    raise TimeoutError(
                        f"backend lock {self.path} held past "
                        f"{self.timeout_s}s"
                    )
                time.sleep(0.002)

    def __exit__(self, *exc) -> None:
        try:
            # Unlink only OUR lock: if a peer broke us as stale and a
            # successor now holds the path, its token differs and the
            # live lock is left alone.
            with open(self.path, "rb") as f:
                if f.read().decode() != self._token:
                    return
            os.unlink(self.path)
        except OSError:
            pass  # broken as stale by a peer — already gone


class _ThreadAndFileMutex:
    """The filesystem backends' RMW guard: in-process threads
    serialize on ``thread_lock``, processes on a :class:`_FsMutex`
    over ``lock_path`` — the file lock is held only for the sub-ms
    meta read-modify-write."""

    def __init__(
        self,
        thread_lock: "threading.RLock",
        lock_path: str,
        wall_clock: Callable[[], float],
    ):
        self._thread_lock = thread_lock
        self._lock_path = lock_path
        self._wall = wall_clock

    def __enter__(self) -> "_ThreadAndFileMutex":
        self._thread_lock.acquire()
        self._fs = _FsMutex(self._lock_path, self._wall)
        try:
            self._fs.__enter__()
        except BaseException:
            self._thread_lock.release()
            raise
        return self

    def __exit__(self, *exc) -> None:
        try:
            self._fs.__exit__(*exc)
        finally:
            self._thread_lock.release()


class FsObjectBackend(SnapshotBackend):
    """Filesystem-simulated object store under one directory: the
    snapshot document lives as a VERSIONED object (``snapshot.v<N>``,
    written atomically) and ``meta.json`` holds the current version +
    lease — so a torn object write can never be observed (the meta
    still points at the previous object) and two processes CAS against
    one directory through the lock-file mutex.  This is the shape a
    real S3/GCS backend would take (conditional PUT on a generation
    number); shipping it filesystem-simulated keeps the whole protocol
    tier-1-testable."""

    kind = "object"

    #: Old object generations kept for readers mid-swap.
    KEEP_OBJECTS = 2

    def __init__(
        self, directory: str,
        wall_clock: Callable[[], float] = time.time,
    ):
        super().__init__(wall_clock)
        if not directory:
            raise ValueError("backend directory must be non-empty")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._meta_path = os.path.join(self.directory, "meta.json")
        self._lock_path = os.path.join(self.directory, "lock")
        self._thread_lock = threading.RLock()

    def _mutex(self):
        return _ThreadAndFileMutex(
            self._thread_lock, self._lock_path, self._wall
        )

    def _object_path(self, version: int) -> str:
        return os.path.join(self.directory, f"snapshot.v{int(version)}")

    def _load_state(self) -> Dict[str, Any]:
        try:
            with open(self._meta_path, "rb") as f:
                meta = json.loads(f.read().decode("utf-8"))
            return self._norm_state(meta)
        except (OSError, ValueError):
            return self._norm_state({})

    def _store_state(self, state: Dict[str, Any]) -> None:
        atomic_write_bytes(
            self._meta_path,
            json.dumps(
                self._norm_state(state), sort_keys=True
            ).encode("utf-8"),
        )

    def _read_data(self, state: Dict[str, Any]) -> Optional[bytes]:
        version = int(state["object_version"])
        if version <= 0:
            return None
        try:
            with open(self._object_path(version), "rb") as f:
                return f.read()
        except FileNotFoundError:
            # Meta points at a GC'd/never-landed object: genuinely
            # nothing to read (a counted "missing" load).  Any OTHER
            # I/O fault (EACCES, EIO) must propagate so the store's
            # fail-open load reports a logged COLD start — a real disk
            # fault may not masquerade as a fresh install.
            return None

    def _write_data(self, data: bytes, new_version: int) -> None:
        atomic_write_bytes(self._object_path(new_version), data)
        # GC generations older than the keep window (best-effort).
        doomed = new_version - self.KEEP_OBJECTS
        while doomed > 0:
            path = self._object_path(doomed)
            if not os.path.exists(path):
                break
            try:
                os.unlink(path)
            except OSError:
                break
            doomed -= 1

    def describe(self) -> str:
        return f"object://{self.directory}"


class FileBackend(SnapshotBackend):
    """The round-12 per-instance atomic local file, as a backend: the
    snapshot document lives at ``path`` byte-for-byte as before (the
    corruption matrix, operator tooling, and hand-tampering tests all
    still read it directly), and CAS/lease metadata appears in a
    sidecar ``<path>.meta`` ONLY once fencing is actually used — an
    unfenced deployment's disk layout is exactly round 12's one file.
    Cross-host CAS is not this backend's claim (one file on one host);
    in-process fencing serializes on the thread lock and
    cross-process-on-one-host fencing on the lock-file mutex — both
    are held for every read-modify-write cycle."""

    kind = "file"

    def __init__(
        self, path: str, wall_clock: Callable[[], float] = time.time
    ):
        super().__init__(wall_clock)
        if not path:
            raise ValueError("snapshot path must be non-empty")
        self.path = str(path)
        self._meta_path = f"{self.path}.meta"
        self._lock_path = f"{self.path}.lock"
        self._thread_lock = threading.RLock()
        # In-memory version counter serving until (unless) the sidecar
        # meta exists; monotone within this process either way.
        self._mem_version = 0

    def _mutex(self):
        # Same composition as FsObjectBackend: without the file lock
        # two processes could both read fence_token=N and mint the
        # SAME token N+1 — the exact lost-update fencing exists to
        # prevent.  The lock file is transient (created and removed
        # around each sub-ms RMW), so the unfenced one-file disk
        # layout is preserved between operations.
        return _ThreadAndFileMutex(
            self._thread_lock, self._lock_path, self._wall
        )

    def _meta_engaged(self) -> bool:
        return os.path.exists(self._meta_path)

    def _load_state(self) -> Dict[str, Any]:
        if self._meta_engaged():
            try:
                with open(self._meta_path, "rb") as f:
                    meta = json.loads(f.read().decode("utf-8"))
                return self._norm_state(meta)
            except (OSError, ValueError):
                pass  # corrupt sidecar: fall through to memory state
        return self._norm_state(
            {"object_version": self._mem_version}
        )

    def _store_state(self, state: Dict[str, Any]) -> None:
        self._mem_version = int(state["object_version"])
        # The sidecar exists only once a lease engaged fencing (or it
        # already exists and must stay coherent): an unfenced
        # deployment keeps the exact round-12 one-file layout.
        if state.get("lease") is not None or self._meta_engaged():
            atomic_write_bytes(
                self._meta_path,
                json.dumps(
                    self._norm_state(state), sort_keys=True
                ).encode("utf-8"),
            )

    def _read_data(self, state: Dict[str, Any]) -> Optional[bytes]:
        try:
            with open(self.path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            # The round-12 "missing" semantics: no file = first boot.
            # Every other OSError (EACCES, EIO, IsADirectoryError)
            # propagates into the store's fail-open load — a logged
            # cold start, never a clean-looking fresh install.
            return None

    def _write_data(self, data: bytes, new_version: int) -> None:
        atomic_write_bytes(self.path, data)

    def describe(self) -> str:
        return self.path


def build_backend(
    kind: str, path: str,
    wall_clock: Callable[[], float] = time.time,
) -> SnapshotBackend:
    """Backend factory for the config/service layer: ``kind`` is one
    of :data:`BACKEND_KINDS`; ``path`` is the file (``file``), the
    shared cell name (``memory``), or the store directory
    (``object``)."""
    if kind == "file":
        return FileBackend(path, wall_clock=wall_clock)
    if kind == "memory":
        return InMemoryBackend(path, wall_clock=wall_clock)
    if kind == "object":
        return FsObjectBackend(path, wall_clock=wall_clock)
    raise ValueError(
        f"unknown snapshot backend {kind!r}; valid: {list(BACKEND_KINDS)}"
    )


class LoadResult:
    """One load's outcome: the verified section bodies, what was
    skipped, and the snapshot's age (seconds at load time, from the
    file's own ``written_at`` wall-clock stamp)."""

    __slots__ = ("outcome", "sections", "skipped", "age_s", "reason")

    def __init__(
        self,
        outcome: str,
        sections: Dict[str, Any],
        skipped: List[str],
        age_s: Optional[float],
        reason: Optional[str] = None,
    ):
        self.outcome = outcome
        self.sections = sections
        self.skipped = skipped
        self.age_s = age_s
        self.reason = reason


class SnapshotStore:
    """Owns one snapshot location: atomic save, corruption-tolerant
    load, and — when a lease is attached — epoch-fenced writes.

    ``wall_clock`` stamps ``written_at`` (it must survive restarts, so
    it is wall time, not the registry's perf counter); durations still
    flow through the registry clock.  Thread-safe: saves serialize on
    an internal lock (the periodic writer, a churn trigger, and the
    drain's final snapshot may race).

    Persistence flows through ``backend`` (:class:`SnapshotBackend`);
    a plain ``path`` keeps the round-12 behavior (a
    :class:`FileBackend` with unconditional writes until fencing is
    attached)."""

    def __init__(
        self,
        path: Optional[str] = None,
        wall_clock: Callable[[], float] = time.time,
        backend: Optional[SnapshotBackend] = None,
    ):
        if backend is None:
            if not path:
                raise ValueError("snapshot path must be non-empty")
            backend = FileBackend(path, wall_clock=wall_clock)
        self.backend = backend
        self.path = backend.describe()
        self._wall = wall_clock
        self._lock = threading.Lock()
        # Last successful save's wall stamp + size, for the lifecycle
        # stats surface (None until a save succeeds or a load finds a
        # file).
        self._last_written_at: Optional[float] = None
        self._last_bytes: Optional[int] = None
        # Last object version this store observed (load or save): the
        # prev_version its fenced CAS writes are conditioned on.
        self._version = 0
        # Writer-lease state (attach_lease/acquire_lease): fencing is
        # OFF until attached — unconditional legacy writes.
        self._lease_owner: Optional[str] = None
        self._lease_ttl_s = 0.0
        self._lease: Optional[Lease] = None
        self._m_writes = {
            o: metrics.REGISTRY.counter(
                "klba_snapshot_writes_total", {"outcome": o}
            )
            for o in ("ok", "error", "fenced", "no_lease")
        }
        self._m_write_ms = metrics.REGISTRY.histogram(
            "klba_snapshot_write_duration_ms"
        )
        self._m_bytes = metrics.REGISTRY.gauge("klba_snapshot_bytes")
        self._m_loads = {
            o: metrics.REGISTRY.counter(
                "klba_snapshot_loads_total", {"outcome": o}
            )
            for o in LOAD_OUTCOMES
        }
        self._m_cas = metrics.REGISTRY.counter(
            "klba_snapshot_cas_conflicts_total"
        )

    # -- writer lease ------------------------------------------------------

    @property
    def fencing_enabled(self) -> bool:
        return self._lease_owner is not None

    @property
    def lease_token(self) -> Optional[int]:
        """The held writer lease's fencing token (None when fencing is
        off or the lease was not acquired) — what the federation plane
        stamps on peer-bound payloads so a fenced-off predecessor's
        sync requests are rejected by its peers too."""
        with self._lock:
            return self._lease.token if self._lease is not None else None

    def attach_lease(self, owner: str, ttl_s: float) -> None:
        """Engage epoch fencing: every subsequent save requires the
        lease acquired via :meth:`acquire_lease` and is a
        ``save_if(token, prev_version)`` against the backend."""
        if not owner:
            raise ValueError("lease owner must be non-empty")
        if not ttl_s > 0:
            raise ValueError(f"lease ttl_s={ttl_s} must be > 0")
        self._lease_owner = str(owner)
        self._lease_ttl_s = float(ttl_s)

    def acquire_lease(
        self,
        wait_s: float = 0.0,
        poll_s: float = 0.05,
        sleep: Callable[[float], None] = time.sleep,
    ) -> Dict[str, Any]:
        """Acquire (or take over) the writer lease, waiting up to
        ``wait_s`` for a live foreign lease to expire or be released.
        NEVER raises — a backend outage must not fail the boot; the
        caller serves anyway and writes are denied (``no_lease``).
        Returns ``{ok, token?, waited_ms, previous_holder,
        previous_expired, error?}``."""
        if not self.fencing_enabled:
            return {"ok": True, "waited_ms": 0.0, "token": None,
                    "previous_holder": None, "previous_expired": False}
        started = self._wall()
        deadline = started + max(float(wait_s), 0.0)
        prev_holder: Optional[str] = None
        prev_expired = False
        while True:
            try:
                try:
                    ls = self.backend.lease_state()
                except Exception:  # noqa: BLE001 — observational read
                    LOGGER.warning(
                        "could not read the current lease holder",
                        exc_info=True,
                    )
                    ls = {}
                held = ls.get("lease")
                released = ls.get("released")
                if held is not None and held["owner"] != self._lease_owner:
                    prev_holder = str(held["owner"])
                    prev_expired = (
                        float(held["expires_at"]) <= self._wall()
                    )
                elif released is not None and (
                    released.get("owner") != self._lease_owner
                ):
                    # The predecessor DRAINED: it released the lease
                    # after its final snapshot — a hand-off, not a
                    # crash (the service reports the mode).
                    prev_holder = str(released.get("owner"))
                    prev_expired = False
                lease = self.backend.acquire_lease(
                    self._lease_owner, self._lease_ttl_s
                )
                with self._lock:
                    self._lease = lease
                waited_ms = (self._wall() - started) * 1000.0
                metrics.REGISTRY.counter(
                    "klba_lease_acquires_total", {"outcome": "acquired"}
                ).inc()
                if prev_holder is not None:
                    metrics.REGISTRY.counter(
                        "klba_lease_takeovers_total",
                        {
                            "previous": (
                                "expired" if prev_expired else "released"
                            )
                        },
                    ).inc()
                return {
                    "ok": True,
                    "token": lease.token,
                    "waited_ms": waited_ms,
                    "previous_holder": prev_holder,
                    "previous_expired": prev_expired,
                }
            except LeaseHeld as exc:
                prev_holder = exc.owner
                prev_expired = False
                now = self._wall()
                if now >= deadline:
                    metrics.REGISTRY.counter(
                        "klba_lease_acquires_total",
                        {"outcome": "timeout"},
                    ).inc()
                    LOGGER.warning(
                        "writer lease still held by %r after %.1fs; "
                        "serving WITHOUT the lease (snapshot writes "
                        "denied until acquired)", exc.owner, wait_s,
                    )
                    return {
                        "ok": False,
                        "waited_ms": (now - started) * 1000.0,
                        "previous_holder": prev_holder,
                        "previous_expired": False,
                        "error": str(exc),
                    }
                sleep(min(poll_s, max(deadline - now, 0.0)))
            except Exception as exc:  # noqa: BLE001 — boot fail-open
                LOGGER.warning(
                    "lease acquisition failed; serving WITHOUT the "
                    "lease (snapshot writes denied)", exc_info=True,
                )
                metrics.REGISTRY.counter(
                    "klba_lease_acquires_total", {"outcome": "error"}
                ).inc()
                return {
                    "ok": False,
                    "waited_ms": (self._wall() - started) * 1000.0,
                    "previous_holder": prev_holder,
                    "previous_expired": prev_expired,
                    "error": str(exc),
                }

    def release_lease(self) -> None:
        """Drop the held lease (graceful drain: the replacement then
        acquires without waiting out the TTL).  Fail-open."""
        with self._lock:
            lease, self._lease = self._lease, None
        if lease is None:
            return
        try:
            self.backend.release_lease(lease)
            metrics.REGISTRY.counter("klba_lease_releases_total").inc()
        except Exception:  # noqa: BLE001 — drain must complete
            LOGGER.warning(
                "lease release failed; the TTL will expire it",
                exc_info=True,
            )

    def lease_stats(self) -> Dict[str, Any]:
        """The lifecycle surface's lease row: this store's fencing
        state plus the backend's CURRENT holder (fail-open to
        unknown)."""
        with self._lock:
            mine = self._lease
        out: Dict[str, Any] = {
            "enabled": self.fencing_enabled,
            "owner": self._lease_owner,
            "ttl_s": self._lease_ttl_s if self.fencing_enabled else None,
            "token": mine.token if mine is not None else None,
            "held": False,
        }
        if not self.fencing_enabled:
            return out
        try:
            holder = self.backend.read_lease()
        except Exception:  # noqa: BLE001 — monitoring read
            LOGGER.warning("lease holder read failed", exc_info=True)
            holder = None
        now = self._wall()
        if holder is not None:
            out["holder"] = holder.owner
            out["holder_token"] = holder.token
            out["holder_age_s"] = max(0.0, now - holder.acquired_at)
            out["expires_in_s"] = holder.expires_at - now
            out["held"] = (
                mine is not None and holder.token == mine.token
            )
        else:
            out["holder"] = None
        return out

    # -- save --------------------------------------------------------------

    def save(self, sections: Dict[str, Any]) -> Dict[str, Any]:
        """Write one snapshot atomically; NEVER raises (a snapshot
        volume outage must not take the service down).  Returns
        ``{"ok", "bytes", "duration_ms"[, "error", "fenced",
        "denied"]}``.  Fault point ``snapshot.write`` fires first — an
        injected failure exercises exactly the fail-open path a full
        disk would.

        With fencing attached this is ``save_if(token, prev_version)``:
        the write carries the held lease's fencing token and the last
        observed object version.  A :class:`CASConflict` (our version
        info went stale — only same-token writers can race us, so the
        token stays authoritative) is retried once against the
        re-read version; a :class:`FencedWriter` (a replacement holds
        a newer lease) is REJECTED loudly — counted, flight-recorded —
        and this store stops pretending to own the state."""
        started = metrics.REGISTRY.clock()
        try:
            faults.fire("snapshot.write")
            if self.fencing_enabled and self._lease is None:
                # The boot handshake failed (backend blip, lingering
                # predecessor): re-try ONE non-blocking acquisition
                # per save, so the instance regains snapshot coverage
                # at the cadence once the lease frees instead of
                # running uncovered until its next restart.  Outside
                # the store lock — acquire_lease takes it to install
                # the lease.
                self.acquire_lease(wait_s=0.0)
            payload = {
                "format": _FORMAT,
                "version": SNAPSHOT_VERSION,
                "written_at": self._wall(),
                "sections": {
                    name: {"crc32": section_crc(body), "body": body}
                    for name, body in sections.items()
                },
            }
            data = json.dumps(payload, sort_keys=True).encode("utf-8")
            with self._lock:
                token: Optional[int] = None
                prev: Optional[int] = None
                if self.fencing_enabled:
                    lease = self._lease
                    if lease is None:
                        self._m_writes["no_lease"].inc()
                        return {
                            "ok": False, "denied": "no_lease",
                            "error": "no writer lease held",
                        }
                    # Renew ahead of expiry so a healthy cadence never
                    # lets the lease lapse between writes; a lapse
                    # without a successor still writes (the token is
                    # the authority), a superseded renew raises
                    # FencedWriter like the write itself would.
                    now = self._wall()
                    if lease.expires_at - now < self._lease_ttl_s / 2:
                        lease = self.backend.renew_lease(
                            lease, self._lease_ttl_s
                        )
                        self._lease = lease
                    token = lease.token
                    prev = self._version
                try:
                    new_version = self.backend.write_if(
                        data, prev_version=prev, token=token
                    )
                except CASConflict:
                    self._m_cas.inc()
                    if token is None:
                        raise
                    # Same-token conflict: our version info is stale
                    # (an unobserved own write); re-read and retry
                    # ONCE.  A foreign newer writer surfaces as
                    # FencedWriter, never here.
                    LOGGER.warning(
                        "snapshot CAS conflict at version %s; "
                        "re-reading and retrying once", prev,
                    )
                    prev = self.backend.version()
                    new_version = self.backend.write_if(
                        data, prev_version=prev, token=token
                    )
                self._version = new_version
                self._last_written_at = payload["written_at"]
                self._last_bytes = len(data)
        except FencedWriter as exc:
            self._m_writes["fenced"].inc()
            metrics.FLIGHT.record(
                "lifecycle",
                {
                    "event": "fenced_write",
                    "owner": self._lease_owner,
                    "error": str(exc),
                },
            )
            LOGGER.warning(
                "snapshot save REJECTED by fencing — a replacement "
                "instance owns the state now; this instance must not "
                "write again: %s", exc,
            )
            return {"ok": False, "fenced": True, "error": str(exc)}
        except Exception as exc:  # noqa: BLE001 — fail-open by contract
            LOGGER.warning(
                "snapshot save to %s failed; serving continues on the "
                "previous snapshot", self.path, exc_info=True,
            )
            self._m_writes["error"].inc()
            return {"ok": False, "error": str(exc)}
        duration_ms = (metrics.REGISTRY.clock() - started) * 1000.0
        self._m_writes["ok"].inc()
        self._m_write_ms.observe(duration_ms)
        self._m_bytes.set(len(data))
        return {"ok": True, "bytes": len(data), "duration_ms": duration_ms}

    # -- load --------------------------------------------------------------

    def load(self) -> LoadResult:
        """Read + verify the snapshot; NEVER raises into the serving
        path.  A bad section is skipped and counted; an unusable file
        is a counted cold start.  Fault point ``snapshot.load`` fires
        first (fails open to cold)."""
        skipped: List[str] = []
        try:
            faults.fire("snapshot.load")
            raw, version = self.backend.read()
            with self._lock:
                self._version = version
            if raw is None:
                return self._finish(
                    LoadResult("missing", {}, [], None, "no snapshot file")
                )
            payload = json.loads(raw.decode("utf-8"))
            if (
                not isinstance(payload, dict)
                or payload.get("format") != _FORMAT
            ):
                return self._finish(LoadResult(
                    "cold", {}, [], None, "not a klba snapshot"
                ))
            version = payload.get("version")
            if version != SNAPSHOT_VERSION:
                # Wrong OR future version: a foreign schema is a clean
                # cold start, never a guess (DEPLOYMENT.md versioning
                # policy).
                return self._finish(LoadResult(
                    "cold", {}, [], None,
                    f"snapshot version {version!r} != {SNAPSHOT_VERSION}",
                ))
            written_at = payload.get("written_at")
            age_s = (
                max(0.0, self._wall() - float(written_at))
                if isinstance(written_at, (int, float)) else None
            )
            sections_in = payload.get("sections")
            if not isinstance(sections_in, dict):
                return self._finish(LoadResult(
                    "cold", {}, [], age_s, "sections block missing"
                ))
            sections: Dict[str, Any] = {}
            for name, entry in sections_in.items():
                try:
                    body = entry["body"]
                    if int(entry["crc32"]) != section_crc(body):
                        raise ValueError("checksum mismatch")
                except Exception:  # noqa: BLE001 — skip + count, per section
                    LOGGER.warning(
                        "snapshot section %r failed verification; "
                        "skipping it (other sections still load)",
                        name, exc_info=True,
                    )
                    skipped.append(str(name))
                    metrics.REGISTRY.counter(
                        "klba_snapshot_sections_skipped_total",
                        {"section": str(name)},
                    ).inc()
                    continue
                sections[str(name)] = body
            if isinstance(written_at, (int, float)):
                with self._lock:
                    if self._last_written_at is None:
                        self._last_written_at = float(written_at)
                        self._last_bytes = len(raw)
            if not sections and skipped:
                return self._finish(LoadResult(
                    "cold", {}, skipped, age_s, "every section corrupt"
                ))
            outcome = "partial" if skipped else "ok"
            return self._finish(
                LoadResult(outcome, sections, skipped, age_s)
            )
        except Exception as exc:  # noqa: BLE001 — fail-open by contract
            LOGGER.warning(
                "snapshot load from %s failed; cold start",
                self.path, exc_info=True,
            )
            return self._finish(
                LoadResult("cold", {}, skipped, None, str(exc))
            )

    def _finish(self, result: LoadResult) -> LoadResult:
        self._m_loads[result.outcome].inc()
        if result.outcome != "ok":
            LOGGER.warning(
                "snapshot load outcome=%s skipped=%s reason=%s",
                result.outcome, result.skipped, result.reason,
            )
        return result

    # -- observability ------------------------------------------------------

    def age_s(self) -> Optional[float]:
        """Seconds since the last KNOWN successful write (this process
        or, after a load, the loaded file's stamp); None before
        either."""
        with self._lock:
            if self._last_written_at is None:
                return None
            return max(0.0, self._wall() - self._last_written_at)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            last = self._last_written_at
            size = self._last_bytes
        return {
            "path": self.path,
            "backend": self.backend.kind,
            "age_s": (
                max(0.0, self._wall() - last) if last is not None else None
            ),
            "bytes": size,
            "writes": self._m_writes["ok"].value,
            "write_errors": self._m_writes["error"].value,
            "writes_fenced": self._m_writes["fenced"].value,
        }


class SnapshotWriter:
    """Background snapshot cadence: one daemon thread writes
    ``collect()``'s sections through ``store`` every ``interval_s``,
    plus soon after any :meth:`mark_churn` (debounced — a registration
    storm coalesces into one write, bounded by ``debounce_s``).  The
    writer never raises (the store's save is fail-open); ``close()``
    stops the thread WITHOUT a final write — the drain path owns the
    final snapshot explicitly, and a crash by definition never gets
    one."""

    def __init__(
        self,
        store: SnapshotStore,
        collect: Callable[[], Dict[str, Any]],
        interval_s: float = 30.0,
        debounce_s: float = 0.2,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        self._store = store
        self._collect = collect
        self.interval_s = float(interval_s)
        self.debounce_s = min(float(debounce_s), self.interval_s)
        self._cond = threading.Condition()
        self._churn = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SnapshotWriter":
        with self._cond:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="klba-snapshot", daemon=True
                )
                self._thread.start()
        return self

    def mark_churn(self) -> None:
        """State changed (stream joined/left/poisoned, membership
        moved): write a snapshot soon, ahead of the cadence."""
        with self._cond:
            self._churn = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def write_now(self) -> Dict[str, Any]:
        """One synchronous snapshot through the store (the drain's
        final write and the operator's on-demand path).  Runs as a
        self-rooted ``background`` trace (root ``snapshot.write``)
        linked to every stream whose warm state it persisted — lease
        activity inside the store's save lands in the same trace."""
        with metrics.request_scope(
            kind="background", root_name="snapshot.write"
        ):
            try:
                payload = self._collect()
                tr = metrics.current_trace()
                if tr is not None:
                    for sid in (payload.get("streams") or {}):
                        tr.link_stream(sid)
                return self._store.save(payload)
            except Exception as exc:  # noqa: BLE001 — collector fail-open
                LOGGER.warning(
                    "snapshot collection failed; skipping this write",
                    exc_info=True,
                )
                return {"ok": False, "error": str(exc)}

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._closed and not self._churn:
                    self._cond.wait(self.interval_s)
                if self._closed:
                    return
                churned = self._churn
            if churned:
                # Debounce a churn burst into one write; a close during
                # the debounce still exits without writing (the drain
                # owns the final snapshot).
                with self._cond:
                    self._cond.wait(self.debounce_s)
                    if self._closed:
                        return
                    self._churn = False
            self.write_now()
