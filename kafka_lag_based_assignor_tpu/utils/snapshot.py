"""Crash-safe lifecycle snapshots: the sidecar's warm state, durable.

Rounds 7 and 11 hardened the service against *external* failures, but
every byte of warm state — per-stream choices and rosters, SLO classes,
the recommend call's lag-trend windows, breaker cooldowns, the overload
rung — lived only in process memory.  A deploy or crash therefore
cold-started ALL tenants at once: the self-inflicted stampede the
round-11 shed ladder exists to survive, and a blackout for the
elasticity loop (the lag history an external autoscaler projects from,
arXiv:2402.06085).  This module makes restarts a non-event: the
service periodically (and on churn) snapshots its host-recoverable
state, and a restarting process rehydrates from it off the serving
path (see service.py's recovery and DEPLOYMENT.md "Restarts and
recovery").

Format (one JSON document)::

    {"format": "klba-snapshot", "version": 1, "written_at": <unix s>,
     "sections": {"streams":  {"crc32": <int>, "body": {...}},
                  "breakers": {"crc32": <int>, "body": {...}},
                  "overload": {"crc32": <int>, "body": {...}}}}

Design rules, in failure-model order:

* **Atomic**: a snapshot is written to a same-directory temp file and
  ``os.rename``-d into place (:func:`atomic_write_bytes` — THE helper
  every durable package write must go through, lint rule L015), so a
  crash mid-write leaves the previous snapshot intact and a reader can
  never observe a torn file from this writer.
* **Versioned**: a loader only trusts ``version == SNAPSHOT_VERSION``.
  A WRONG version (older writer) and a FUTURE version (newer writer, a
  rolled-back deploy) both load as a counted cold start — never a
  guess at a foreign schema.
* **Per-section checksummed**: each section's body carries a CRC32 of
  its canonical JSON encoding.  A corrupt section (bit rot, a torn
  copy) is SKIPPED and counted — the other sections still load; losing
  the breaker states must not cost every tenant its warm roster.
* **Fail-open**: :meth:`SnapshotStore.load` never raises into the
  serving path.  Anything unreadable — missing file, truncated JSON,
  wrong format marker — is a counted cold start; anything partially
  readable is a counted partial load.  :meth:`SnapshotStore.save`
  never raises either (an outage of the snapshot volume must not take
  the sidecar down); failures land in
  ``klba_snapshot_writes_total{outcome="error"}``.

Fault points (utils/faults, wired into the chaos suite):
``snapshot.write`` fires at the head of every save, ``snapshot.load``
at the head of every load — both exercise the fail-open contracts
above.

Telemetry: ``klba_snapshot_writes_total{outcome}``,
``klba_snapshot_write_duration_ms``, ``klba_snapshot_bytes``,
``klba_snapshot_loads_total{outcome}``,
``klba_snapshot_sections_skipped_total{section}``.

Clock discipline: durations flow through the registry clock (L012);
``written_at`` / snapshot age need a WALL clock that survives a
process restart, so the store takes an injectable ``wall_clock``
defaulting to ``time.time`` (referenced, never called directly).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

from . import faults, metrics

LOGGER = logging.getLogger(__name__)

#: The schema version THIS writer produces and the only one the loader
#: trusts.  Bump it on any incompatible body change; the rollout story
#: (DEPLOYMENT.md "Restarts and recovery") is that a version mismatch
#: is a clean cold start, never a migration attempt in the sidecar.
SNAPSHOT_VERSION = 1

_FORMAT = "klba-snapshot"

#: Load outcomes, the ``klba_snapshot_loads_total`` label values:
#: ``ok`` (every section verified), ``partial`` (>= 1 section skipped),
#: ``cold`` (nothing usable: corrupt/wrong-version/unreadable),
#: ``missing`` (no file — the normal first boot).
LOAD_OUTCOMES = ("ok", "partial", "cold", "missing")


def _canonical(body: Any) -> bytes:
    """THE byte encoding the section checksums are computed over —
    shared by save and load so the two can never disagree on
    whitespace or key order."""
    return json.dumps(
        body, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")


def section_crc(body: Any) -> int:
    """CRC32 of a section body's canonical encoding (exposed so tests
    can build hand-tampered snapshots)."""
    return zlib.crc32(_canonical(body))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """THE durable-write helper (lint rule L015): write ``data`` to a
    same-directory temp file, fsync, then ``os.rename`` over ``path``.
    A reader can observe the old file or the new file, never a torn
    mix; a crash mid-write leaves the old file untouched.  The temp
    name carries the pid so two processes pointed at one path cannot
    corrupt each other's staging (last rename still wins, atomically).
    """
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        # Never leave staging litter next to the real file; the rename
        # either happened (tmp is gone) or the write is abandoned.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class LoadResult:
    """One load's outcome: the verified section bodies, what was
    skipped, and the snapshot's age (seconds at load time, from the
    file's own ``written_at`` wall-clock stamp)."""

    __slots__ = ("outcome", "sections", "skipped", "age_s", "reason")

    def __init__(
        self,
        outcome: str,
        sections: Dict[str, Any],
        skipped: List[str],
        age_s: Optional[float],
        reason: Optional[str] = None,
    ):
        self.outcome = outcome
        self.sections = sections
        self.skipped = skipped
        self.age_s = age_s
        self.reason = reason


class SnapshotStore:
    """Owns one snapshot path: atomic save, corruption-tolerant load.

    ``wall_clock`` stamps ``written_at`` (it must survive restarts, so
    it is wall time, not the registry's perf counter); durations still
    flow through the registry clock.  Thread-safe: saves serialize on
    an internal lock (the periodic writer, a churn trigger, and the
    drain's final snapshot may race)."""

    def __init__(
        self,
        path: str,
        wall_clock: Callable[[], float] = time.time,
    ):
        if not path:
            raise ValueError("snapshot path must be non-empty")
        self.path = str(path)
        self._wall = wall_clock
        self._lock = threading.Lock()
        # Last successful save's wall stamp + size, for the lifecycle
        # stats surface (None until a save succeeds or a load finds a
        # file).
        self._last_written_at: Optional[float] = None
        self._last_bytes: Optional[int] = None
        self._m_writes = {
            o: metrics.REGISTRY.counter(
                "klba_snapshot_writes_total", {"outcome": o}
            )
            for o in ("ok", "error")
        }
        self._m_write_ms = metrics.REGISTRY.histogram(
            "klba_snapshot_write_duration_ms"
        )
        self._m_bytes = metrics.REGISTRY.gauge("klba_snapshot_bytes")
        self._m_loads = {
            o: metrics.REGISTRY.counter(
                "klba_snapshot_loads_total", {"outcome": o}
            )
            for o in LOAD_OUTCOMES
        }

    # -- save --------------------------------------------------------------

    def save(self, sections: Dict[str, Any]) -> Dict[str, Any]:
        """Write one snapshot atomically; NEVER raises (a snapshot
        volume outage must not take the service down).  Returns
        ``{"ok", "bytes", "duration_ms"[, "error"]}``.  Fault point
        ``snapshot.write`` fires first — an injected failure exercises
        exactly the fail-open path a full disk would."""
        started = metrics.REGISTRY.clock()
        try:
            faults.fire("snapshot.write")
            payload = {
                "format": _FORMAT,
                "version": SNAPSHOT_VERSION,
                "written_at": self._wall(),
                "sections": {
                    name: {"crc32": section_crc(body), "body": body}
                    for name, body in sections.items()
                },
            }
            data = json.dumps(payload, sort_keys=True).encode("utf-8")
            with self._lock:
                atomic_write_bytes(self.path, data)
                self._last_written_at = payload["written_at"]
                self._last_bytes = len(data)
        except Exception as exc:  # noqa: BLE001 — fail-open by contract
            LOGGER.warning(
                "snapshot save to %s failed; serving continues on the "
                "previous snapshot", self.path, exc_info=True,
            )
            self._m_writes["error"].inc()
            return {"ok": False, "error": str(exc)}
        duration_ms = (metrics.REGISTRY.clock() - started) * 1000.0
        self._m_writes["ok"].inc()
        self._m_write_ms.observe(duration_ms)
        self._m_bytes.set(len(data))
        return {"ok": True, "bytes": len(data), "duration_ms": duration_ms}

    # -- load --------------------------------------------------------------

    def load(self) -> LoadResult:
        """Read + verify the snapshot; NEVER raises into the serving
        path.  A bad section is skipped and counted; an unusable file
        is a counted cold start.  Fault point ``snapshot.load`` fires
        first (fails open to cold)."""
        skipped: List[str] = []
        try:
            faults.fire("snapshot.load")
            try:
                with open(self.path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                return self._finish(
                    LoadResult("missing", {}, [], None, "no snapshot file")
                )
            payload = json.loads(raw.decode("utf-8"))
            if (
                not isinstance(payload, dict)
                or payload.get("format") != _FORMAT
            ):
                return self._finish(LoadResult(
                    "cold", {}, [], None, "not a klba snapshot"
                ))
            version = payload.get("version")
            if version != SNAPSHOT_VERSION:
                # Wrong OR future version: a foreign schema is a clean
                # cold start, never a guess (DEPLOYMENT.md versioning
                # policy).
                return self._finish(LoadResult(
                    "cold", {}, [], None,
                    f"snapshot version {version!r} != {SNAPSHOT_VERSION}",
                ))
            written_at = payload.get("written_at")
            age_s = (
                max(0.0, self._wall() - float(written_at))
                if isinstance(written_at, (int, float)) else None
            )
            sections_in = payload.get("sections")
            if not isinstance(sections_in, dict):
                return self._finish(LoadResult(
                    "cold", {}, [], age_s, "sections block missing"
                ))
            sections: Dict[str, Any] = {}
            for name, entry in sections_in.items():
                try:
                    body = entry["body"]
                    if int(entry["crc32"]) != section_crc(body):
                        raise ValueError("checksum mismatch")
                except Exception:  # noqa: BLE001 — skip + count, per section
                    LOGGER.warning(
                        "snapshot section %r failed verification; "
                        "skipping it (other sections still load)",
                        name, exc_info=True,
                    )
                    skipped.append(str(name))
                    metrics.REGISTRY.counter(
                        "klba_snapshot_sections_skipped_total",
                        {"section": str(name)},
                    ).inc()
                    continue
                sections[str(name)] = body
            if isinstance(written_at, (int, float)):
                with self._lock:
                    if self._last_written_at is None:
                        self._last_written_at = float(written_at)
                        self._last_bytes = len(raw)
            if not sections and skipped:
                return self._finish(LoadResult(
                    "cold", {}, skipped, age_s, "every section corrupt"
                ))
            outcome = "partial" if skipped else "ok"
            return self._finish(
                LoadResult(outcome, sections, skipped, age_s)
            )
        except Exception as exc:  # noqa: BLE001 — fail-open by contract
            LOGGER.warning(
                "snapshot load from %s failed; cold start",
                self.path, exc_info=True,
            )
            return self._finish(
                LoadResult("cold", {}, skipped, None, str(exc))
            )

    def _finish(self, result: LoadResult) -> LoadResult:
        self._m_loads[result.outcome].inc()
        if result.outcome != "ok":
            LOGGER.warning(
                "snapshot load outcome=%s skipped=%s reason=%s",
                result.outcome, result.skipped, result.reason,
            )
        return result

    # -- observability ------------------------------------------------------

    def age_s(self) -> Optional[float]:
        """Seconds since the last KNOWN successful write (this process
        or, after a load, the loaded file's stamp); None before
        either."""
        with self._lock:
            if self._last_written_at is None:
                return None
            return max(0.0, self._wall() - self._last_written_at)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            last = self._last_written_at
            size = self._last_bytes
        return {
            "path": self.path,
            "age_s": (
                max(0.0, self._wall() - last) if last is not None else None
            ),
            "bytes": size,
            "writes": self._m_writes["ok"].value,
            "write_errors": self._m_writes["error"].value,
        }


class SnapshotWriter:
    """Background snapshot cadence: one daemon thread writes
    ``collect()``'s sections through ``store`` every ``interval_s``,
    plus soon after any :meth:`mark_churn` (debounced — a registration
    storm coalesces into one write, bounded by ``debounce_s``).  The
    writer never raises (the store's save is fail-open); ``close()``
    stops the thread WITHOUT a final write — the drain path owns the
    final snapshot explicitly, and a crash by definition never gets
    one."""

    def __init__(
        self,
        store: SnapshotStore,
        collect: Callable[[], Dict[str, Any]],
        interval_s: float = 30.0,
        debounce_s: float = 0.2,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0")
        self._store = store
        self._collect = collect
        self.interval_s = float(interval_s)
        self.debounce_s = min(float(debounce_s), self.interval_s)
        self._cond = threading.Condition()
        self._churn = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SnapshotWriter":
        with self._cond:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="klba-snapshot", daemon=True
                )
                self._thread.start()
        return self

    def mark_churn(self) -> None:
        """State changed (stream joined/left/poisoned, membership
        moved): write a snapshot soon, ahead of the cadence."""
        with self._cond:
            self._churn = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def write_now(self) -> Dict[str, Any]:
        """One synchronous snapshot through the store (the drain's
        final write and the operator's on-demand path)."""
        try:
            return self._store.save(self._collect())
        except Exception as exc:  # noqa: BLE001 — collector fail-open
            LOGGER.warning(
                "snapshot collection failed; skipping this write",
                exc_info=True,
            )
            return {"ok": False, "error": str(exc)}

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._closed and not self._churn:
                    self._cond.wait(self.interval_s)
                if self._closed:
                    return
                churned = self._churn
            if churned:
                # Debounce a churn burst into one write; a close during
                # the debounce still exits without writing (the drain
                # owns the final snapshot).
                with self._cond:
                    self._cond.wait(self.debounce_s)
                    if self._closed:
                        return
                    self._churn = False
            self.write_now()
