"""Deterministic fault injection for failure-domain drills.

The sidecar deployment (PAPER L1/L3 split) adds failure domains the
reference never had: a wedged accelerator transport, a poisoned warm
stream, a half-dead XLA compile, flaky broker RPCs.  The hardening for
those domains (per-solver circuit breakers, the degraded-mode ladder,
bounded lag retry) is only trustworthy if it is *fault-tested* — so the
code paths carry named fault points and this module injects failures at
them, deterministically and reproducibly.

Named fault points (every one threaded through production code):

================  =====================================================
``device.solve``    entry of the accelerated solve
                    (:meth:`..assignor.LagBasedPartitionAssignor._solve_accelerated`)
``device.compile``  per-group kernel dispatch, where a fresh XLA compile
                    would occur (:func:`..ops.dispatch.assign_group_device`)
``stream.refine``   entry of a streaming rebalance epoch
                    (:meth:`..ops.streaming.StreamingAssignor.rebalance`)
``coalesce.flush``  the megabatch coalescer's per-group flush
                    (:meth:`..ops.coalesce.MegabatchCoalescer._flush`) —
                    a failure here exercises the batched-epoch isolation
                    path (every row re-dispatches single-stream)
``coalesce.gather`` resident-row materialization out of a locked
                    roster batch (:meth:`..ops.coalesce.ResidentRow.
                    materialize`) — the roster-churn recovery path: a
                    failure here exercises a stream's exit from the
                    batch (inline dispatch, re-stack, row fallback)
``admit.park``      a warm epoch parking in the megabatch coalescer's
                    admission queue (:meth:`..ops.coalesce.
                    MegabatchCoalescer.submit`) — a failure here
                    exercises the submitter's degraded-mode ladder
                    (the epoch never entered a wave)
``shed.decide``     the overload controller's admission decision
                    (:meth:`..utils.overload.OverloadController.
                    admission`) — the service FAILS OPEN (admits) when
                    the shed decision itself faults
``delta.diff``      the host-side lag differ (:meth:`..ops.streaming.
                    StreamingAssignor._delta_plan` and
                    :class:`..lag.LagDeltaTracker`) — a failure here
                    must fall back to the dense upload within the same
                    epoch, warm state intact, no breaker charge
``delta.apply``     the fused delta dispatch (inline
                    :meth:`..ops.streaming.StreamingAssignor.
                    _dispatch_delta` and the coalescer's stacked delta
                    staging) — fires BEFORE any donation, so a failure
                    falls back to the dense upload within the same
                    request budget
``device.corrupt.choice`` / ``device.corrupt.counts`` /
``device.corrupt.lags`` / ``device.corrupt.row_tab``
                    seeded BIT-FLIP injection into the named
                    device-resident buffer at a readback boundary
                    (:meth:`..ops.streaming.StreamingAssignor.
                    _adopt_resident` and the megabatch coalescer's
                    locked readback) — unlike every other point, a
                    firing plan does not raise into the caller: the
                    buffer is silently corrupted (host mirror left
                    intact) so the integrity plane (per-epoch fused
                    digests + the utils/scrub auditor) must DETECT the
                    divergence, quarantine the stream/row, and heal it
                    bit-exact from host truth.  Use ``raise`` plans;
                    the seed picks the flipped element and bit
``mesh.collective`` entry of a SHARDED dispatch (the P-sharded solve's
                    :func:`..sharded.solve.solve_sharded` /
                    ``refine_sharded`` and the coalescer's stream-sharded
                    locked flush via
                    :meth:`..sharded.mesh.MeshManager.check_collective`)
                    — a lost device / failed collective: the mesh
                    manager DEGRADES to the single-device backend and
                    the in-flight request walks the existing ladder
                    (single-device cold solve, single-stream flush
                    fallback) inside its deadline — no invalid
                    assignment is ever served off a half-dead mesh
``snapshot.write``  a lifecycle snapshot save (:meth:`..utils.snapshot.
                    SnapshotStore.save`) — a failure here exercises the
                    fail-open write contract (serving continues on the
                    previous snapshot, counted as a write error)
``snapshot.load``   boot-time snapshot load (:meth:`..utils.snapshot.
                    SnapshotStore.load`) — a failure here exercises the
                    fail-open recovery contract (counted cold start,
                    never an exception into the serving path)
``snapshot.cas``    a conditional (versioned) backend write
                    (:meth:`..utils.snapshot.SnapshotBackend.write_if`)
                    — fires as a simulated CAS RACE: the write loses
                    cleanly (CASConflict), the store retries once per
                    its contract, serving is never taken down
``snapshot.lease``  writer-lease acquire/renew/release
                    (:class:`..utils.snapshot.SnapshotBackend`) — a
                    boot that cannot acquire the lease serves anyway
                    with snapshot writes denied (fail-open takeover)
``backend.partition``  entry of EVERY snapshot-backend operation — an
                    unreachable remote store: saves count errors,
                    loads count cold starts, assignment never stops
``backend.latency`` same entry, latency mode — a slow remote link:
                    the operation proceeds after the injected delay
                    (pair with ``latency`` plans; a ``raise`` plan
                    here behaves like ``backend.partition``)
``peer.partition``  entry of a federation peer RPC
                    (:meth:`..federated.peers._PeerLink.request`) — an
                    unreachable peer: the exchange round is abandoned
                    and the sidecar degrades down the federation
                    ladder (last-good-global duals, then local-only)
``peer.slow_link``  same entry, latency mode — a slow inter-cluster
                    link: the RPC proceeds after the injected delay,
                    bounded by the per-peer sync timeout AND the
                    request's remaining deadline budget (pair with
                    ``latency`` plans)
``peer.sync``       inside the breaker-wrapped peer exchange
                    (:meth:`..federated.peers.FederationCoordinator.
                    _sync_once`) — a protocol-level sync failure:
                    charged to that peer's circuit breaker
                    (consecutive failures trip it)
``peer.stale_duals``  the initiator's response validation (same
                    method) — a firing plan makes the peer's answer
                    count as STALE state: dropped and counted in
                    ``klba_peer_stale_duals_total``, never averaged
                    into the global marginals
``drain.flush``     the graceful drain's coalescer quiesce
                    (:meth:`..ops.coalesce.MegabatchCoalescer.drain`)
                    — a failure here must not stop the drain from
                    writing its final snapshot and closing the listener
``lag.begin``       the ListOffsets(beginning) broker RPC (:mod:`..lag`)
``lag.end``         the ListOffsets(end) broker RPC
``lag.committed``   the OffsetFetch broker RPC
``wire.read``       the sidecar's per-line socket read (:mod:`..service`)
================  =====================================================

Fault modes: ``raise`` (raise :class:`FaultError`), ``hang`` (bounded
sleep of ``delay_s`` then raise — simulates a wedged transport that the
watchdog must abandon; the sleep is clamped so a drill can never wedge
the process itself), ``latency`` (sleep then proceed normally).

Zero-cost when off: production code calls :func:`fire`, which is a
single global load + ``None`` compare unless an injector was activated
(the warm rebalance loop's bench gate pins this: no new compiles, warm
p50 unchanged).

Determinism: plans fire by *call count* (``after`` skips, ``times``
bounds), and the optional ``probability`` coin uses the injector's own
seeded :class:`random.Random` — the same seed replays the same schedule.

Exact schedules (:meth:`FaultInjector.schedule`): where a drill needs a
fault at a *known* boundary rather than a seeded coin — the scenario
fleet's fault-schedule composer (scenarios/compose.py), a soak's phase
boundary — a plan can pin firing to exact call numbers (``at_calls``)
and/or to trace epochs (``at_epochs``, advanced by the driver via
:meth:`FaultInjector.set_epoch`; ``per_epoch`` bounds firings inside
each eligible epoch).  Scheduled plans are fully deterministic: no
probability coin, no hand-counted ``after`` warm-up offsets.

Activation: programmatic (``activate`` / the ``injected`` context
manager) or by environment for staging drills::

    KLBA_FAULTS="device.solve:raise:2,lag.end:latency:3:0.01"
    KLBA_FAULTS_SEED=7

Spec grammar per entry: ``point:mode[:times[:delay_s[:probability]]]``;
``times`` <= 0 means unlimited.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Iterator, List, Mapping, Optional, Sequence

from . import metrics

LOGGER = logging.getLogger(__name__)

#: Every fault point compiled into production code.  ``plan()`` validates
#: against this set so a typo'd drill fails loudly instead of never firing.
FAULT_POINTS = frozenset(
    {
        "device.solve",
        "device.compile",
        "stream.refine",
        "coalesce.flush",
        "coalesce.gather",
        "admit.park",
        "shed.decide",
        "delta.diff",
        "delta.apply",
        "device.corrupt.choice",
        "device.corrupt.counts",
        "device.corrupt.lags",
        "device.corrupt.row_tab",
        "mesh.collective",
        "peer.partition",
        "peer.slow_link",
        "peer.sync",
        "peer.stale_duals",
        "snapshot.write",
        "snapshot.load",
        "snapshot.cas",
        "snapshot.lease",
        "backend.partition",
        "backend.latency",
        "drain.flush",
        "lag.begin",
        "lag.end",
        "lag.committed",
        "wire.read",
    }
)

_MODES = ("raise", "hang", "latency")

# A "hang" must be bounded: the drill simulates a wedge for the watchdog
# to abandon, it must never actually wedge the process running the drill.
MAX_HANG_S = 60.0

ENV_SPEC = "KLBA_FAULTS"
ENV_SEED = "KLBA_FAULTS_SEED"


class FaultError(RuntimeError):
    """The injected failure (``raise`` and post-``hang`` modes)."""


@dataclass
class FaultPlan:
    """One point's schedule: fire on eligible calls ``after`` < n <=
    ``after + times`` (call counting starts at 1; ``times`` <= 0 means
    every call past ``after``), each firing gated by the seeded
    ``probability`` coin.

    Exact-schedule plans (:meth:`FaultInjector.schedule`) instead pin
    firing to specific call numbers (``at_calls``) and/or to driver-
    advanced trace epochs (``at_epochs`` + ``per_epoch``); those fields
    replace the probability coin entirely — a scheduled plan fires
    deterministically or not at all."""

    point: str
    mode: str = "raise"
    times: int = 1
    after: int = 0
    delay_s: float = 0.05
    probability: float = 1.0
    fired: int = 0
    at_calls: Optional[frozenset] = None
    at_epochs: Optional[frozenset] = None
    per_epoch: int = 0
    # epoch-local firing bookkeeping (``per_epoch`` accounting)
    epoch_seen: int = -1
    epoch_fired: int = 0


class FaultInjector:
    """A seeded, thread-safe schedule of named faults.

    Plans are per point; :meth:`fire` consults the active plan under a
    lock (counters stay exact across the service's worker threads) and
    sleeps, if at all, outside it.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._plans: Dict[str, FaultPlan] = {}
        self._calls: Dict[str, int] = {}
        self._epoch = 0
        self._lock = threading.Lock()

    def plan(
        self,
        point: str,
        mode: str = "raise",
        times: int = 1,
        after: int = 0,
        delay_s: float = 0.05,
        probability: float = 1.0,
    ) -> "FaultInjector":
        """Register (replace) the plan for ``point``; chainable."""
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; valid: {sorted(FAULT_POINTS)}"
            )
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}; valid: {_MODES}")
        if not 0.0 <= probability <= 1.0:
            raise ValueError(f"probability {probability} not in [0, 1]")
        self._plans[point] = FaultPlan(
            point=point,
            mode=mode,
            times=int(times),
            after=int(after),
            delay_s=min(float(delay_s), MAX_HANG_S),
            probability=float(probability),
        )
        return self

    def schedule(
        self,
        point: str,
        mode: str = "raise",
        *,
        at_calls: Optional[Sequence[int]] = None,
        at_epochs: Optional[Sequence[int]] = None,
        per_epoch: int = 1,
        delay_s: float = 0.05,
    ) -> "FaultInjector":
        """Register an EXACT schedule for ``point``; chainable.

        Unlike :meth:`plan` (seeded probability + after/times call
        windows), a scheduled plan fires deterministically: at the
        listed call numbers (``at_calls``, 1-based — the injector's own
        per-point counter), and/or only inside the listed trace epochs
        (``at_epochs`` — the driver advances the clock via
        :meth:`set_epoch`; ``per_epoch`` bounds firings per eligible
        epoch, <= 0 = every eligible call).  With only ``at_epochs``
        given, the first ``per_epoch`` calls of each listed epoch
        fire — the scenario fleet's composer (scenarios/compose.py)
        builds its merged fault overlays exactly this way, and a soak
        can pin a phase boundary without hand-counting warm-up calls."""
        if at_calls is None and at_epochs is None:
            raise ValueError(
                "schedule() needs at_calls and/or at_epochs; use plan() "
                "for probabilistic/windowed firing"
            )
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; valid: {sorted(FAULT_POINTS)}"
            )
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}; valid: {_MODES}")
        for name, seq in (("at_calls", at_calls), ("at_epochs", at_epochs)):
            if seq is not None and any(int(n) < 0 for n in seq):
                raise ValueError(f"{name} entries must be >= 0: {seq!r}")
        self._plans[point] = FaultPlan(
            point=point,
            mode=mode,
            times=0,  # unlimited: the schedule itself bounds firing
            delay_s=min(float(delay_s), MAX_HANG_S),
            at_calls=(
                None if at_calls is None
                else frozenset(int(n) for n in at_calls)
            ),
            at_epochs=(
                None if at_epochs is None
                else frozenset(int(n) for n in at_epochs)
            ),
            per_epoch=int(per_epoch),
        )
        return self

    def set_epoch(self, epoch: int) -> None:
        """Advance the schedule clock: ``at_epochs`` plans are eligible
        only while the driver-declared epoch is in their set."""
        with self._lock:
            self._epoch = int(epoch)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def calls(self, point: str) -> int:
        """Times ``fire`` was reached for ``point`` (fault or not)."""
        with self._lock:
            return self._calls.get(point, 0)

    def fired(self, point: str) -> int:
        """Faults actually injected at ``point``."""
        with self._lock:
            plan = self._plans.get(point)
            return plan.fired if plan is not None else 0

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-point ``{calls, fired}`` counters (drill observability)."""
        with self._lock:
            return {
                point: {
                    "calls": self._calls.get(point, 0),
                    "fired": plan.fired,
                }
                for point, plan in self._plans.items()
            }

    def fire(self, point: str) -> None:
        """Execute the plan for ``point`` against this call (see class
        docstring); no-op for unplanned points."""
        with self._lock:
            n = self._calls.get(point, 0) + 1
            self._calls[point] = n
            plan = self._plans.get(point)
            if plan is None or n <= plan.after:
                return
            if plan.at_epochs is not None:
                if self._epoch not in plan.at_epochs:
                    return
                if plan.per_epoch > 0:
                    if plan.epoch_seen != self._epoch:
                        plan.epoch_seen = self._epoch
                        plan.epoch_fired = 0
                    if plan.epoch_fired >= plan.per_epoch:
                        return
            if plan.at_calls is not None and n not in plan.at_calls:
                return
            if plan.times > 0 and plan.fired >= plan.times:
                return
            if plan.probability < 1.0 and (
                self._rng.random() >= plan.probability
            ):
                return
            plan.fired += 1
            if plan.at_epochs is not None and plan.per_epoch > 0:
                plan.epoch_fired += 1
            mode, delay = plan.mode, plan.delay_s
        # Registry export (utils/metrics): fault activations as a
        # queryable series.  Recorded OUTSIDE the injector lock and only
        # on the fired path — the off path stays the one global load +
        # None compare in :func:`fire` below.
        metrics.REGISTRY.counter(
            "klba_fault_fired_total", {"point": point, "mode": mode}
        ).inc()
        # Sleeps happen OUTSIDE the lock: a hang drill must wedge only
        # the faulted call, not every other fault point in the process.
        if mode == "latency":
            time.sleep(delay)
            return
        if mode == "hang":
            time.sleep(delay)
            raise FaultError(
                f"injected hang at {point!r} ({delay:.3f}s, call {n})"
            )
        raise FaultError(f"injected fault at {point!r} (call {n})")


# The active injector.  ``fire`` below is the production hook: ONE global
# load + None compare when no drill is running.
_ACTIVE: Optional[FaultInjector] = None


def fire(point: str) -> None:
    """The hook compiled into production fault points (zero-cost off)."""
    inj = _ACTIVE
    if inj is not None:
        inj.fire(point)


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def activate(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = injector
    LOGGER.warning(
        "fault injection ACTIVE (seed=%d, plans=%s)",
        injector.seed, sorted(injector._plans),
    )
    return injector


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextmanager
def injected(injector: FaultInjector) -> Iterator[FaultInjector]:
    """Scope an injector to a block (tests, drills)."""
    activate(injector)
    try:
        yield injector
    finally:
        deactivate()


def parse_spec(spec: str, seed: int = 0) -> FaultInjector:
    """Build an injector from the ``KLBA_FAULTS`` grammar (see module
    docstring); raises ValueError on malformed entries."""
    inj = FaultInjector(seed)
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault spec {entry!r} must be "
                "'point:mode[:times[:delay_s[:probability]]]'"
            )
        point, mode = parts[0], parts[1]
        try:
            times = int(parts[2]) if len(parts) > 2 else 1
            delay_s = float(parts[3]) if len(parts) > 3 else 0.05
            probability = float(parts[4]) if len(parts) > 4 else 1.0
        except ValueError:
            raise ValueError(f"fault spec {entry!r} has non-numeric fields")
        inj.plan(
            point, mode=mode, times=times, delay_s=delay_s,
            probability=probability,
        )
    return inj


def install_from_env(
    env: Optional[Mapping[str, str]] = None,
) -> Optional[FaultInjector]:
    """Activate an injector from ``KLBA_FAULTS`` / ``KLBA_FAULTS_SEED``
    (staging drills); returns it, or None when the variable is unset.
    Called once at import so a drill needs no code change."""
    env = os.environ if env is None else env
    spec = env.get(ENV_SPEC)
    if not spec:
        return None
    seed = int(env.get(ENV_SEED, "0"))
    return activate(parse_spec(spec, seed=seed))


def fault_points() -> List[str]:
    return sorted(FAULT_POINTS)


install_from_env()
