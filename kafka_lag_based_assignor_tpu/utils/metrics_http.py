"""Plain-HTTP ``/metrics`` listener (opt-in): stock-Prometheus scrapes.

The unified registry (utils/metrics) has been queryable over the JSON
wire as ``{"method": "metrics"}`` since round 8 — but a stock Prometheus
server speaks HTTP GET, not newline-JSON over TCP, so scraping the
sidecar required a shim.  This module serves the SAME registry as the
standard text exposition (version 0.0.4) on a plain HTTP port:

* ``GET /metrics``  -> 200, ``text/plain; version=0.0.4``,
  :meth:`Registry.prometheus` of the process-wide registry;
* ``GET /healthz``  -> 200 ``ok`` (liveness for the scrape target);
* anything else     -> 404.

Opt-in: the sidecar binds it only when a metrics port is configured
(``AssignorService(metrics_port=...)`` / the ``--metrics-port`` flag /
``tpu.assignor.metrics.port``).  Port 0 asks the OS for a free port
(tests); the bound address is exposed as :attr:`MetricsHTTPServer.address`.

Read-only by construction: the handler renders a snapshot and never
touches service state, so exposing it on an observability network is
safe (the JSON wire stays the only mutating surface).
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from . import metrics

LOGGER = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 — http.server's contract
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = metrics.REGISTRY.prometheus().encode()
            self._reply(200, body, CONTENT_TYPE)
        elif path == "/healthz":
            self._reply(200, b"ok\n", "text/plain; charset=utf-8")
        else:
            self._reply(
                404, b"not found (try /metrics)\n",
                "text/plain; charset=utf-8",
            )

    def _reply(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:
        # Route http.server's stderr chatter through logging instead.
        LOGGER.debug("metrics-http %s", fmt % args)


class MetricsHTTPServer:
    """Threaded HTTP front end over the process-wide registry."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._http.server_address[:2]
        return str(host), int(port)

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(
            target=self._http.serve_forever,
            name="klba-metrics-http", daemon=True,
        )
        self._thread.start()
        LOGGER.info("metrics listener on http://%s:%d/metrics",
                    *self.address)
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    def __enter__(self) -> "MetricsHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
