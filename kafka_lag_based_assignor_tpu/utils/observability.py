"""Structured observability for rebalances.

The reference's observability is slf4j logging: debug config summary
(LagBasedPartitionAssignor.java:122-128), trace per-assignment decisions
(:268-275), debug per-topic totals (:280-306), warn on missing metadata
(:359).  Here the per-rebalance record is structured — per-consumer totals,
the max/mean lag-imbalance ratio (the north-star metric), count spread, and
wall/kernel timings — and emitted both as a log line and as a returned
value so callers and benches can consume it programmatically.

``profile_trace`` wraps a rebalance in a ``jax.profiler`` trace for
Perfetto/TensorBoard inspection (SURVEY §5 tracing row).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterator, List, Optional

from . import metrics
from . import trace as trace_mod

LOGGER = logging.getLogger("kafka_lag_based_assignor_tpu")

# slf4j has a TRACE level below DEBUG (the reference logs every
# partition->consumer decision at trace, LagBasedPartitionAssignor.java:268-275);
# Python's logging does not, so register one.
TRACE = 5
logging.addLevelName(TRACE, "TRACE")


# --- Compile observability ---------------------------------------------
#
# A fresh XLA compile on the latency-critical rebalance path is THE
# silent performance cliff of this system (tens of seconds through a
# remote-compile transport; the r5 warm-path regression hid exactly
# there).  Two counters make it observable and assertable:
#
# * ``compile_count()`` — fresh backend compiles seen process-wide, fed
#   by jax.monitoring's backend-compile duration event.  A cached
#   executable fires no event, so the steady-state warm loop can assert
#   a ZERO delta (bench.py's ``warm_compile_count``; the regression test
#   in tests/test_streaming.py).
# * ``static_drift_count()`` — value-derived STATIC kernel args observed
#   changing per call signature (ops/dispatch.observe_pack_shift), i.e.
#   recompiles caused by input value ranges drifting across a packing
#   bound rather than by new shapes.
#
# Both live in the unified registry (utils/metrics) as
# ``klba_compile_total`` / ``klba_static_drift_total``; the functions
# here are the stable pre-registry API over those series.

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compile_listener_installed = [False]
_COMPILES = metrics.REGISTRY.counter("klba_compile_total")
_STATIC_DRIFT = metrics.REGISTRY.counter("klba_static_drift_total")


def install_compile_counter() -> None:
    """Idempotently register the jax.monitoring listener behind
    :func:`compile_count`.  Call once at process setup (warm-up, bench,
    service start) BEFORE the executables of interest are built; compiles
    that happen earlier are simply not counted."""
    if _compile_listener_installed[0]:
        return
    from jax import monitoring

    def _on_duration(name, *_args, **_kw):
        if name == _COMPILE_EVENT:
            _COMPILES.inc()

    monitoring.register_event_duration_secs_listener(_on_duration)
    _compile_listener_installed[0] = True


def compile_count() -> int:
    """Fresh XLA backend compiles observed since
    :func:`install_compile_counter` (0 if never installed).  Snapshot it
    around a steady-state loop and assert the delta is zero."""
    return _COMPILES.value


def note_static_drift() -> None:
    """Record one observed static-kernel-arg drift (called by
    ops/dispatch.observe_pack_shift when a call signature's value-derived
    static args change — each such change compiles a fresh executable
    unless the variant was warmed)."""
    _STATIC_DRIFT.inc()


def static_drift_count() -> int:
    return _STATIC_DRIFT.value


# --- Breaker observability ---------------------------------------------
#
# Process-wide trip counters per circuit-breaker key (utils/watchdog) —
# the aggregate behind every Watchdog instance, so a deployment can
# assert "no breaker tripped during this soak" without reaching into
# individual watchdogs (the per-instance state lives in Watchdog.stats()
# and the service `stats` method).  Backed by the registry's
# ``klba_breaker_trips_total{key=...}`` series — which also fixes the
# old torn read: the previous dict snapshot was built WITHOUT the
# writers' lock; registry children always read under their own lock.
# A trip is also a flight-recorder trigger (utils/metrics.FLIGHT): the
# incident's ring of recent epoch records is dumped exactly once.

_TRIPS_NAME = "klba_breaker_trips_total"


def note_breaker_trip(key: str) -> None:
    """Record one breaker trip (called by utils/watchdog on every
    closed/half-open -> open transition).  Also an always-keep anomaly
    on the active trace — the request that tripped the breaker is
    exactly the one tail sampling must retain."""
    trace_mod.mark("breaker")
    metrics.REGISTRY.counter(_TRIPS_NAME, {"key": key}).inc()
    metrics.FLIGHT.auto_dump("breaker_trip", {"key": key})


def breaker_trip_counts() -> Dict[str, int]:
    """Per-key trips since process start (empty if none ever tripped)."""
    return {
        c.labels["key"]: c.value
        for c in metrics.REGISTRY.series(_TRIPS_NAME)
        if c.value
    }


def breaker_trip_count(key: Optional[str] = None) -> int:
    """Total trips, or one key's trips.  Read-only: querying a key that
    never tripped must NOT mint a zero-valued series into the registry
    (a monitoring probe asserting "no trips" would otherwise grow the
    Prometheus exposition with every key it ever asked about)."""
    return sum(
        c.value for c in metrics.REGISTRY.series(_TRIPS_NAME)
        if key is None or c.labels.get("key") == key
    )


def count_constrained_bound(lags, num_consumers: int) -> float:
    """Input-driven lower bound on max/mean lag imbalance for ANY valid
    assignment — THE normalizer for the north-star quality metric.

    Two facts force the floor: (1) the hottest partition sits on SOME
    consumer; (2) the count-primary invariant (max - min partitions <= 1,
    reference :246-249) forces that consumer to hold at least floor(P/C)
    partitions, each contributing its (non-negative) lag.  So
    ``peak >= max_lag + sum of the floor(P/C)-1 smallest other lags`` and
    ``bound = peak_min / mean_member_load``.  Dominates the naive
    ``max_lag / mean`` bound and is tight in practice: the refined
    Sinkhorn assignment lands on it exactly on the Zipf 1k x 16 bench
    config (achieved == bound to 7 digits).  Shared by the benchmark's
    quality_ratio and the streaming engine's guardrail so both agree on
    what "optimal" means.
    """
    import numpy as np

    lags = np.asarray(lags)
    C = int(num_consumers)
    mean = lags.sum() / C if C else 0.0
    if mean <= 0:
        return 1.0
    k = max(lags.shape[0] // C - 1, 0)
    extra = np.partition(lags, k)[:k].sum() if k > 0 else 0
    return float((lags.max() + extra) / mean)


@dataclass
class RebalanceStats:
    """One rebalance's structured record."""

    num_topics: int = 0
    num_partitions: int = 0
    num_members: int = 0
    solver: str = ""
    # One-shot quality-mode budget applied on top of the solver (None =
    # strict reference parity) — operators reading a rebalance record must
    # be able to tell whether an assignment is refined or bit-parity.
    refine_iters: Optional[int] = None
    fallback_used: bool = False
    # The configured solver's circuit-breaker state at response time
    # (utils/watchdog: closed | open | half_open; None = no watchdog) —
    # an operator reading a fallback_used record must be able to tell a
    # one-off failure (closed) from a sidelined device (open).
    breaker_state: Optional[str] = None
    wall_ms: float = 0.0
    lag_read_ms: float = 0.0
    solve_ms: float = 0.0
    total_lag: int = 0
    # Per-member totals across all topics (host-aggregated).
    member_total_lag: Dict[str, int] = field(default_factory=dict)
    member_partition_count: Dict[str, int] = field(default_factory=dict)
    # Per-topic breakdown: topic -> member -> {"count": n, "total_lag": L}.
    # The structured analog of the reference's per-topic debug summary block
    # (LagBasedPartitionAssignor.java:280-306).
    per_topic: Dict[str, Dict[str, Dict[str, int]]] = field(
        default_factory=dict
    )
    # Count-constrained lower bound on the imbalance for this rebalance's
    # input (see count_constrained_bound) — filled by summarize_assignment.
    # Exact for the uniform-subscription case (every member subscribes to
    # every topic, incl. all single-topic groups); with asymmetric
    # subscriptions the count floor may not bind every member, so treat
    # the recorded value as a normalizer, not a proof of optimality.
    imbalance_bound: float = 1.0

    @property
    def max_mean_lag_imbalance(self) -> float:
        """max(member lag) / mean(member lag) — 1.0 is perfect; no valid
        assignment can score below ``imbalance_bound``."""
        lags = list(self.member_total_lag.values())
        if not lags:
            return 1.0
        mean = sum(lags) / len(lags)
        return max(lags) / mean if mean > 0 else 1.0

    @property
    def quality_ratio(self) -> float:
        """Achieved imbalance normalized to the input-driven bound — the
        north-star quality metric; 1.0 means provably optimal for the
        input (same normalization as the benchmark's quality_ratio)."""
        return self.max_mean_lag_imbalance / max(self.imbalance_bound, 1.0)

    @property
    def count_spread(self) -> int:
        counts = list(self.member_partition_count.values())
        return (max(counts) - min(counts)) if counts else 0

    def to_json(self) -> str:
        d = asdict(self)
        d["max_mean_lag_imbalance"] = self.max_mean_lag_imbalance
        d["count_spread"] = self.count_spread
        d["quality_ratio"] = self.quality_ratio
        return json.dumps(d, sort_keys=True)


def summarize_assignment(
    stats: RebalanceStats,
    assignment: Dict[str, List],
    lag_by_tp: Dict,
) -> RebalanceStats:
    """Fill member totals from an assignment map and a TopicPartition->lag
    map, plus the input-driven imbalance bound over the ASSIGNED rows."""
    for member, tps in assignment.items():
        stats.member_partition_count[member] = len(tps)
        stats.member_total_lag[member] = sum(lag_by_tp.get(tp, 0) for tp in tps)
    if lag_by_tp and stats.num_members:
        import numpy as np

        stats.imbalance_bound = count_constrained_bound(
            np.fromiter(lag_by_tp.values(), dtype=np.int64,
                        count=len(lag_by_tp)),
            stats.num_members,
        )
    return stats


def summarize_topics(
    stats: RebalanceStats,
    assignment: Dict[str, List],
    lags: Dict[str, List],
) -> RebalanceStats:
    """Fill the per-topic member count/total-lag breakdown.

    ``lags`` maps topic -> list of TopicPartitionLag rows (the core's input);
    ``assignment`` maps member -> list of TopicPartition.  Mirrors the data
    the reference aggregates for its per-topic debug block
    (LagBasedPartitionAssignor.java:280-306), but structured.
    """
    lag_of = {
        (r.topic, r.partition): r.lag for rows in lags.values() for r in rows
    }
    for member, tps in assignment.items():
        for tp in tps:
            entry = stats.per_topic.setdefault(tp.topic, {}).setdefault(
                member, {"count": 0, "total_lag": 0}
            )
            entry["count"] += 1
            entry["total_lag"] += lag_of.get((tp.topic, tp.partition), 0)
    return stats


def replay_decisions(
    assignment: Dict[str, List], lags: Dict[str, List]
) -> Iterator[tuple]:
    """Reconstruct the per-partition decision sequence from a finished
    assignment.

    The core consumes each topic's partitions in a deterministic order (lag
    descending, partition id ascending — reference :228-235), so the decision
    sequence, including each member's running total at decision time, is
    recoverable host-side from the result alone.  That lets the trace work
    identically for the host oracle and the device kernels, without threading
    logging through jit-compiled code.

    Only meaningful for the reference-parity solvers (``rounds``/``scan``/
    ``native``/``host``), whose decisions ARE per-topic sequential greedy;
    for ``global`` (cross-topic totals) or ``sinkhorn`` (no sequential
    decisions at all) the replayed running totals would be fiction — callers
    must not trace those solvers.

    Yields ``(topic, partition, member, partition_lag, member_running_total)``
    — the exact fields of the reference's trace line (:268-275).
    """
    member_of = {
        (tp.topic, tp.partition): member
        for member, tps in assignment.items()
        for tp in tps
    }
    for topic, rows in lags.items():
        ordered = sorted(rows, key=lambda r: (-r.lag, r.partition))
        running: Dict[str, int] = {}
        for r in ordered:
            member = member_of.get((topic, r.partition))
            if member is None:  # topic had no eligible consumers
                continue
            running[member] = running.get(member, 0) + r.lag
            yield (topic, r.partition, member, r.lag, running[member])


def trace_decisions(
    assignment: Dict[str, List],
    lags: Dict[str, List],
    logger: logging.Logger = LOGGER,
) -> None:
    """Opt-in per-decision trace, reference format (:268-275)."""
    for topic, partition, member, lag, total in replay_decisions(
        assignment, lags
    ):
        logger.log(
            TRACE,
            "Assigned partition %s-%d to consumer %s.  partition_lag=%d, "
            "consumer_current_total_lag=%d",
            topic,
            partition,
            member,
            lag,
            total,
        )


def log_topic_summaries(
    stats: RebalanceStats,
    assignment: Dict[str, List],
    logger: logging.Logger = LOGGER,
) -> None:
    """Debug-level per-topic summary block, reference format (:280-306)."""
    if not logger.isEnabledFor(logging.DEBUG):
        return
    # One O(total partitions) grouping pass, then O(1) lookups per line.
    grouped: Dict[str, Dict[str, List]] = {}
    for member, tps in assignment.items():
        for tp in tps:
            grouped.setdefault(tp.topic, {}).setdefault(member, []).append(tp)
    for topic, members in stats.per_topic.items():
        lines = []
        for member, entry in members.items():
            lines.append(f"\t{member} (total_lag={entry['total_lag']})\n")
            for tp in grouped.get(topic, {}).get(member, ()):
                lines.append(f"\t\t{tp.topic}-{tp.partition}\n")
        logger.debug("Assignment for %s:\n%s", topic, "".join(lines))


def log_rebalance(stats: RebalanceStats) -> None:
    LOGGER.info("rebalance %s", stats.to_json())


@contextlib.contextmanager
def stopwatch() -> Iterator[List[float]]:
    """``with stopwatch() as t: ...`` -> ``t[0]`` is elapsed milliseconds."""
    out = [0.0]
    start = time.perf_counter()
    try:
        yield out
    finally:
        out[0] = (time.perf_counter() - start) * 1000.0


@contextlib.contextmanager
def profile_trace(enabled: bool, log_dir: str = "/tmp/klba_tpu_trace"):
    """Optionally wrap a block in a jax.profiler trace (Perfetto-compatible)."""
    if not enabled:
        yield None
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield log_dir
