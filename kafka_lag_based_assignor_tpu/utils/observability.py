"""Structured observability for rebalances.

The reference's observability is slf4j logging: debug config summary
(LagBasedPartitionAssignor.java:122-128), trace per-assignment decisions
(:268-275), debug per-topic totals (:280-306), warn on missing metadata
(:359).  Here the per-rebalance record is structured — per-consumer totals,
the max/mean lag-imbalance ratio (the north-star metric), count spread, and
wall/kernel timings — and emitted both as a log line and as a returned
value so callers and benches can consume it programmatically.

``profile_trace`` wraps a rebalance in a ``jax.profiler`` trace for
Perfetto/TensorBoard inspection (SURVEY §5 tracing row).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterator, List, Optional

LOGGER = logging.getLogger("kafka_lag_based_assignor_tpu")


@dataclass
class RebalanceStats:
    """One rebalance's structured record."""

    num_topics: int = 0
    num_partitions: int = 0
    num_members: int = 0
    solver: str = ""
    fallback_used: bool = False
    wall_ms: float = 0.0
    lag_read_ms: float = 0.0
    solve_ms: float = 0.0
    total_lag: int = 0
    # Per-member totals across all topics (host-aggregated).
    member_total_lag: Dict[str, int] = field(default_factory=dict)
    member_partition_count: Dict[str, int] = field(default_factory=dict)

    @property
    def max_mean_lag_imbalance(self) -> float:
        """max(member lag) / mean(member lag) — 1.0 is perfect, and the
        input-driven lower bound is max_partition_lag / mean(member lag)."""
        lags = list(self.member_total_lag.values())
        if not lags:
            return 1.0
        mean = sum(lags) / len(lags)
        return max(lags) / mean if mean > 0 else 1.0

    @property
    def count_spread(self) -> int:
        counts = list(self.member_partition_count.values())
        return (max(counts) - min(counts)) if counts else 0

    def to_json(self) -> str:
        d = asdict(self)
        d["max_mean_lag_imbalance"] = self.max_mean_lag_imbalance
        d["count_spread"] = self.count_spread
        return json.dumps(d, sort_keys=True)


def summarize_assignment(
    stats: RebalanceStats,
    assignment: Dict[str, List],
    lag_by_tp: Dict,
) -> RebalanceStats:
    """Fill member totals from an assignment map and a TopicPartition->lag map."""
    for member, tps in assignment.items():
        stats.member_partition_count[member] = len(tps)
        stats.member_total_lag[member] = sum(lag_by_tp.get(tp, 0) for tp in tps)
    return stats


def log_rebalance(stats: RebalanceStats) -> None:
    LOGGER.info("rebalance %s", stats.to_json())


@contextlib.contextmanager
def stopwatch() -> Iterator[List[float]]:
    """``with stopwatch() as t: ...`` -> ``t[0]`` is elapsed milliseconds."""
    out = [0.0]
    start = time.perf_counter()
    try:
        yield out
    finally:
        out[0] = (time.perf_counter() - start) * 1000.0


@contextlib.contextmanager
def profile_trace(enabled: bool, log_dir: str = "/tmp/klba_tpu_trace"):
    """Optionally wrap a block in a jax.profiler trace (Perfetto-compatible)."""
    if not enabled:
        yield None
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield log_dir
