"""Typed configuration layer.

The reference receives an untyped ``Map<String,?>`` through Kafka's
``Configurable`` SPI (LagBasedPartitionAssignor.java:97-130) and consumes:
``group.id`` (required, :107-113), ``auto.offset.reset`` (default "latest",
:346-347), and derives metadata-consumer overrides
``enable.auto.commit=false`` + ``client.id=<group.id>.assignor`` (:116-120).

This module reproduces those pass-through semantics exactly and adds the
framework's own typed knobs (solver choice, shape buckets, fallback policy)
under a ``tpu.assignor.`` key prefix — unknown Kafka keys pass through
untouched, as the reference copies the whole map (:101-104).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

GROUP_ID_CONFIG = "group.id"
AUTO_OFFSET_RESET_CONFIG = "auto.offset.reset"
ENABLE_AUTO_COMMIT_CONFIG = "enable.auto.commit"
CLIENT_ID_CONFIG = "client.id"
PARTITION_ASSIGNMENT_STRATEGY_CONFIG = "partition.assignment.strategy"

SOLVER_CONFIG = (
    "tpu.assignor.solver"  # rounds | scan | global | sinkhorn | native | host
)
FALLBACK_CONFIG = "tpu.assignor.host.fallback"  # bool: greedy host fallback
PROFILE_CONFIG = "tpu.assignor.profile"  # bool: jax.profiler traces
SOLVE_TIMEOUT_CONFIG = "tpu.assignor.solve.timeout.ms"  # 0/empty disables
# Circuit-breaker knobs (utils/watchdog): how long a tripped solver stays
# sidelined before the single half-open probe, and how many CONSECUTIVE
# exceptions (not only timeouts) trip the breaker.
BREAKER_COOLDOWN_CONFIG = "tpu.assignor.breaker.cooldown.ms"
BREAKER_FAILURES_CONFIG = "tpu.assignor.breaker.failures"  # int >= 1
# Opt-in bounded retry for the three lag batch RPCs (lag.py): number of
# RETRIES per RPC (0 = reference abort semantics, the default) and the
# deterministic exponential-backoff base delay.
LAG_RETRIES_CONFIG = "tpu.assignor.lag.retries"  # int >= 0
LAG_RETRY_BACKOFF_CONFIG = "tpu.assignor.lag.retry.backoff.ms"
SINKHORN_ITERS_CONFIG = "tpu.assignor.sinkhorn.iters"  # int > 0
# Quality-mode plane (ops/dispatch + ops/linear_ot; DEPLOYMENT.md
# "Quality modes").  ``quality.mode`` routes every quality solve:
# "sinkhorn" pins the dense implicit-plan path, "linear" pins the
# O(P + C)-memory mirror-prox path, "auto" (default) picks linear at
# large row counts or whenever the device mesh elects the P-sharded
# backend for the shape (the linear duals shard over the same mesh).
# ``quality.tile`` is the linear mode's streamed tile size in rows
# (pow2; peak device memory is O(tile*C + P + C)).
QUALITY_MODE_CONFIG = "tpu.assignor.quality.mode"
QUALITY_TILE_CONFIG = "tpu.assignor.quality.tile"

#: Valid ``quality.mode`` values (ops/dispatch mirrors this tuple).
QUALITY_MODES = ("sinkhorn", "linear", "auto")

_MAX_QUALITY_TILE = 1 << 16


def validate_quality_tile(tile) -> int:
    """THE ``quality.tile`` validator — shared by this config key and
    ops/linear_ot (the knob and the executable cannot drift): a power
    of two in [8, 65536]."""
    try:
        t = int(tile)
    except (TypeError, ValueError):
        raise ValueError(f"quality tile {tile!r} is not an integer")
    if t < 8 or t > _MAX_QUALITY_TILE or (t & (t - 1)):
        raise ValueError(
            f"quality tile {t} must be a power of two in "
            f"[8, {_MAX_QUALITY_TILE}]"
        )
    return t
# int >= 0, or unset/"auto".  For the "sinkhorn" solver, "auto" selects
# the per-rounding-path budget (models/sinkhorn: 24 for the sequential
# scan rounding, 96 for the parallel rounding, which starts coarser) and
# an explicit integer is honored exactly.  For the parity solvers
# "rounds"/"scan", an explicit integer > 0 opts into the one-shot quality
# mode (greedy + that many exchange-refinement rounds — NOT bit-parity
# with the reference), while unset/"auto"/0 keeps strict parity.
# Rejected for "global" (per-topic refinement would undo its cross-topic
# balance); ignored by "native"/"host" (host-only paths).
REFINE_ITERS_CONFIG = "tpu.assignor.refine.iters"
# Megabatch coalescer knobs (ops/coalesce, served by the sidecar):
# admission window in ms (how long a warm epoch may wait for same-bucket
# batchmates before its flush; sub-millisecond keeps the lone-tenant
# p50 intact) and the per-shape-bucket batch cap (a full group flushes
# immediately; <= 1 disables cross-stream coalescing entirely).
COALESCE_WINDOW_CONFIG = "tpu.assignor.coalesce.window.ms"
COALESCE_MAX_BATCH_CONFIG = "tpu.assignor.coalesce.max_batch"
# Roster-stable fast path + flush pipeline knobs (ops/coalesce): how
# many consecutive identical-stream-set waves a shape group serves
# before its roster LOCKS (stacked batch buffers stay device-resident,
# rows index-addressed in place — 1 locks on the first megabatch flush;
# a large value effectively disables the fast path), and whether the
# upload/dispatch/readback flush stages overlap across waves (false =
# strict-serial fallback).
COALESCE_LOCK_WAVES_CONFIG = "tpu.assignor.coalesce.roster.lock.waves"
COALESCE_PIPELINE_CONFIG = "tpu.assignor.coalesce.pipeline"
# Delta epochs (ops/streaming; DEPLOYMENT.md "Delta epochs"): whether a
# warm dispatch may scatter-apply a sparse (indices, values) lag update
# onto the device-resident lag buffer instead of re-uploading the full
# vector; the changed-fraction ceiling above which the dense upload is
# used; and the number of pow2 K-ladder rungs (executable count per
# shape bucket — warm-up drives one synthetic delta wave per rung, and
# the megabatch's stacked delta path pads to the ladder top).  0
# buckets disables like enabled=false.
DELTA_ENABLED_CONFIG = "tpu.assignor.delta.enabled"
DELTA_MAX_FRACTION_CONFIG = "tpu.assignor.delta.max.fraction"
DELTA_BUCKETS_CONFIG = "tpu.assignor.delta.buckets"
# Per-stream ADAPTIVE delta cutoff (ops/streaming; ROADMAP delta
# follow-on (b)): each engine tracks its observed churn distribution
# (bounded window) and auto-tunes the delta/dense cutoff within
# [max.fraction/4, min(2*max.fraction, 0.5)] instead of pinning it to
# the one global knob.  The effective fraction surfaces in the stream
# stats, klba_delta_effective_fraction, and dump_metrics --summary.
DELTA_ADAPTIVE_CONFIG = "tpu.assignor.delta.adaptive"
# Multi-device sharding (sharded/; DEPLOYMENT.md "Multi-device
# sharding").  ``mesh.devices`` selects the device mesh discovered and
# validated ONCE at service start: "off" (default — single-device),
# "auto" (all visible devices; single-device when only one is
# visible), or an integer N (exactly N devices; fewer visible degrades
# to single-device at boot, fail-open).  On CPU hosts the virtual mesh
# needs XLA_FLAGS=--xla_force_host_platform_device_count=N set before
# jax initializes.  ``mesh.solve.min.rows`` is the partition-count
# floor below which a single device wins outright and the P-sharded
# solve backend is not selected.
MESH_DEVICES_CONFIG = "tpu.assignor.mesh.devices"
MESH_SOLVE_MIN_ROWS_CONFIG = "tpu.assignor.mesh.solve.min.rows"
# Cross-axis 2-D composition (DEPLOYMENT.md "Cross-axis mesh"):
# ``mesh.shape`` factorizes the mesh.devices pool into an (S, D)
# ("streams", "p") grid — "off" (default, 1-D behaviour), "auto" (the
# most square split favouring "p"), or an explicit "SxD" (e.g. "2x4";
# S*D must equal the validated device count or boot falls down the
# degrade ladder: 2-D -> 1-D streams -> 1-D p -> single device).
MESH_SHAPE_CONFIG = "tpu.assignor.mesh.shape"
# SLO classes + overload control (utils/overload, served by the
# sidecar).  Per-stream class: "tpu.assignor.slo.class.<stream_id>" =
# critical | standard | best_effort (a wire-level params.slo_class
# override wins per request; unlisted streams are "standard").
# Per-class deadline budget: "tpu.assignor.slo.deadline.ms.<class>" —
# caps that class's request budget BELOW solve.timeout.ms and rides
# into the coalescer as the epoch's admission deadline.  The overload
# detector's knobs: the epoch-latency level (ms) treated as pressure
# 1.0 (0/unset = auto: half the solve timeout — permissive, an
# unconfigured sidecar never sheds on cold compiles) and the weighted
# in-flight depth treated as pressure 1.0.
SLO_CLASS_PREFIX = "tpu.assignor.slo.class."
SLO_DEADLINE_PREFIX = "tpu.assignor.slo.deadline.ms."
OVERLOAD_LATENCY_BUDGET_CONFIG = "tpu.assignor.overload.latency.budget.ms"
OVERLOAD_DEPTH_HIGH_CONFIG = "tpu.assignor.overload.depth.high"
# Opt-in plain-HTTP /metrics listener (utils/metrics_http): a port for a
# stock Prometheus to scrape the registry's text exposition without a
# sidecar shim.  0/unset disables (the JSON wire `metrics` method is
# always available).
METRICS_PORT_CONFIG = "tpu.assignor.metrics.port"
# Lifecycle snapshots + graceful drain (utils/snapshot, served by the
# sidecar; DEPLOYMENT.md "Restarts and recovery").  ``snapshot.path``
# names the snapshot FILE (written atomically: tmp + rename) —
# empty/unset disables snapshots AND recovery.  ``snapshot.interval.ms``
# is the periodic write cadence (churn events additionally trigger a
# debounced early write).  ``snapshot.max.age.ms`` is the per-boot
# staleness guard: a snapshot older than this at recovery rehydrates
# NOTHING (counted stale cold start) — lag trends and rosters that old
# are misinformation, not warm state.  ``drain.timeout.ms`` bounds how
# long a graceful drain (SIGTERM / wire ``drain``) waits for in-flight
# requests and coalescer waves before writing the final snapshot and
# closing the listener anyway.
SNAPSHOT_PATH_CONFIG = "tpu.assignor.snapshot.path"
SNAPSHOT_INTERVAL_CONFIG = "tpu.assignor.snapshot.interval.ms"
SNAPSHOT_MAX_AGE_CONFIG = "tpu.assignor.snapshot.max.age.ms"
DRAIN_TIMEOUT_CONFIG = "tpu.assignor.drain.timeout.ms"
# Cross-host hand-off (utils/snapshot backends; DEPLOYMENT.md
# "Cross-host hand-off").  ``snapshot.backend`` selects where the
# snapshot lives: "file" (per-instance local file, the default) or the
# object-store-shaped "memory" / "object" backends with versioned CAS
# writes.  A ``snapshot.lease.ttl.ms`` > 0 engages epoch-fenced writer
# leases: boot acquires the lease (waiting up to
# ``snapshot.lease.wait.ms`` for a crashed predecessor's lease to
# expire; 0 = auto, 2x ttl + 1 s), every save is conditioned on the
# fencing token, and a fenced-off predecessor's writes are rejected
# instead of clobbering the replacement's adopted state.
SNAPSHOT_BACKEND_CONFIG = "tpu.assignor.snapshot.backend"
SNAPSHOT_LEASE_TTL_CONFIG = "tpu.assignor.snapshot.lease.ttl.ms"
SNAPSHOT_LEASE_WAIT_CONFIG = "tpu.assignor.snapshot.lease.wait.ms"
# Post-restart resync pacing: at most this many concurrent
# stale-resident dense rebuild dispatches (the full-vector re-sync a
# recovered stream pays on its first post-restart epoch); excess
# epochs wait their turn (counted ``klba_resync_paced_total``).  0
# disables pacing.
RESYNC_MAX_INFLIGHT_CONFIG = "tpu.assignor.resync.max.inflight"
# Resident-state scrubber cadence (utils/scrub; DEPLOYMENT.md "State
# integrity"): how often the background auditor round-robins idle
# streams' device-resident buffers against their host mirrors.  Each
# pass is deadline-budgeted and skipped while the overload ladder is
# at rung >= 2; a failed audit quarantines the stream (the next epoch
# rebuilds bit-exact from host truth) and repeated failures escalate
# to the stream breaker.  0 disables the background scrubber (the
# per-epoch fused digests stay on either way).
SCRUB_INTERVAL_CONFIG = "tpu.assignor.scrub.interval.ms"
# Pre-stack recovered rosters at boot (ROADMAP lifecycle (b)): rebuild
# each recovered stream's device-resident state from its seeded choice
# off the serving path, so the restart storm's first epochs coalesce
# like steady-state traffic instead of dispatching inline dense
# table-builds.
RECOVERY_PRESTACK_CONFIG = "tpu.assignor.recovery.prestack"
# Federated multi-cluster assignment (federated/; DEPLOYMENT.md
# "Federated assignment").  ``federation.self.id`` is this sidecar's
# stable peer identity (empty/unset disables the whole plane);
# ``federation.peers`` lists the peer sidecars as
# "id=host:port,id=host:port".  ``federation.rounds`` bounds the
# dual-exchange rounds per federated_assign; ``sync.timeout.ms`` is
# the per-peer RPC deadline (also bounded by the request budget);
# ``max.staleness.ms`` bounds how old the last-good-global dual cache
# may be and still serve the middle degradation rung.
FEDERATION_SELF_ID_CONFIG = "tpu.assignor.federation.self.id"
FEDERATION_PEERS_CONFIG = "tpu.assignor.federation.peers"
FEDERATION_ROUNDS_CONFIG = "tpu.assignor.federation.rounds"
FEDERATION_SYNC_TIMEOUT_CONFIG = "tpu.assignor.federation.sync.timeout.ms"
FEDERATION_MAX_STALENESS_CONFIG = (
    "tpu.assignor.federation.max.staleness.ms"
)
# Async gossip duals (ISSUE 19): cadence of the background dual-
# convergence daemon.  0 (the default) disables gossip — every
# federated_assign pays the synchronous exchange; > 0 keeps the duals
# warm so assigns serve rung global from cache in one local round.
FEDERATION_GOSSIP_INTERVAL_CONFIG = (
    "tpu.assignor.federation.gossip.interval.ms"
)
# Weighted shards (ROADMAP federated (c)): this cluster's per-consumer
# capacity weight vector as comma-separated positive floats (length =
# the consumer count federated_assign serves).  Exchanged in the hello
# handshake through the audited federated/wire serializer and summed
# into the global count-marginal target — consumers with more capacity
# take proportionally more partitions.  Empty/unset contributes
# uniform weights (the n/C marginal when no cluster is weighted).
FEDERATION_CAPACITY_CONFIG = "tpu.assignor.federation.capacity"
# "P:C[:T][,P:C[:T]...]" — shapes to pre-compile at configure() time
# (consumer startup, NOT on the rebalance critical path): each entry warms
# the kernels for max_partitions P / num_consumers C / a topic batch of T
# (default 1; multi-topic groups batch at pad_bucket(n_topics), so groups
# subscribing to several topics should warm their T too).  Shared parser
# with the sidecar's --warmup flag (parse_warmup_shapes).  Empty/unset
# skips warm-up.
WARMUP_SHAPES_CONFIG = "tpu.assignor.warmup.shapes"


def parse_warmup_shapes(text: str) -> list:
    """THE parser for warm-up shape lists — used by both this config key
    and the sidecar's ``--warmup`` flag so the two surfaces cannot
    diverge.  Returns [(max_partitions, num_consumers, topics), ...];
    raises ValueError on malformed or non-positive entries."""
    shapes = []
    for pair in str(text).split(","):
        parts = pair.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"warmup shape {pair!r} must be "
                "'max_partitions:num_consumers[:topics]'"
            )
        try:
            nums = [int(p) for p in parts]
        except ValueError:
            raise ValueError(
                f"warmup shape {pair!r} must be "
                "'max_partitions:num_consumers[:topics]'"
            )
        if len(nums) == 2:
            nums.append(1)
        if any(n < 1 for n in nums):
            raise ValueError(
                f"warmup shape entries must be positive, got {pair!r}"
            )
        shapes.append(tuple(nums))
    return shapes

VALID_SOLVERS = ("rounds", "scan", "global", "sinkhorn", "native", "host")

# Solvers with device (XLA) executables — the ones configure-time warm-up
# can usefully pre-compile ("native"/"host" run entirely host-side).
DEVICE_SOLVERS = ("rounds", "scan", "global", "sinkhorn")

# Solvers whose output is bit-identical to the reference's per-topic greedy
# (and therefore whose decision sequence can be replayed for trace logging,
# utils/observability.replay_decisions).
PARITY_SOLVERS = ("rounds", "scan", "native", "host")


@dataclass
class AssignorConfig:
    """Validated view over the consumer config map."""

    group_id: str
    auto_offset_reset: str = "latest"
    solver: str = "rounds"
    host_fallback: bool = True
    profile: bool = False
    # A hung accelerator (wedged transport) must never block a rebalance
    # past its deadline; None disables the watchdog.  The default leaves
    # headroom for first-rebalance XLA compiles (~40 s/shape without a warm
    # persistent cache); a trip only sidelines the accelerator for the
    # watchdog cooldown, not forever.
    solve_timeout_s: Optional[float] = 120.0
    # Circuit-breaker policy: a tripped solver fails fast (host fallback)
    # for the cooldown, then exactly one probe is admitted half-open;
    # breaker_failures consecutive exceptions trip it like a timeout does.
    breaker_cooldown_s: float = 300.0
    breaker_failures: int = 3
    # Lag-RPC retry policy: 0 retries preserves the reference's
    # broker-exception-aborts-the-rebalance semantics exactly.
    lag_retries: int = 0
    lag_retry_backoff_s: float = 0.05
    # Quality-mode iteration budgets (sinkhorn solver / exchange
    # refinement); refine_iters None = per-path auto budget.
    sinkhorn_iters: int = 24
    refine_iters: Optional[int] = None
    # Quality-mode routing + the linear mode's tile size (ops/dispatch
    # / ops/linear_ot; "auto" = linear at scale or under a mesh).
    quality_mode: str = "auto"
    quality_tile: int = 1024
    # Megabatch coalescer (ops/coalesce): admission window + batch cap,
    # roster lock threshold, and the flush-pipeline toggle.
    coalesce_window_s: float = 0.0005
    coalesce_max_batch: int = 32
    coalesce_lock_waves: int = 1
    coalesce_pipeline: bool = True
    # Delta epochs (ops/streaming): sparse lag updates onto the
    # device-resident lag buffer; fraction ceiling + pow2 K ladder.
    delta_enabled: bool = True
    delta_max_fraction: float = 0.125
    delta_buckets: int = 6
    delta_adaptive: bool = True
    # Multi-device sharding (sharded/): mesh spec + P-sharded-solve
    # row floor ("off" = single-device, the default).
    mesh_devices: str = "off"
    mesh_solve_min_rows: int = 65536
    # Cross-axis (S, D) factorization of the mesh ("off" = 1-D rungs).
    mesh_shape: str = "off"
    # SLO classes + overload control (utils/overload): per-stream class
    # map, per-class deadline budgets (seconds), and the overload
    # detector's pressure normalizers (0 latency budget = auto).
    slo_classes: Dict[str, str] = field(default_factory=dict)
    slo_deadline_s: Dict[str, float] = field(default_factory=dict)
    overload_latency_budget_ms: float = 0.0
    overload_depth_high: float = 24.0
    # Plain-HTTP /metrics port (utils/metrics_http); None = disabled.
    metrics_port: Optional[int] = None
    # Lifecycle snapshots + drain (utils/snapshot; None path disables).
    snapshot_path: Optional[str] = None
    snapshot_interval_s: float = 30.0
    snapshot_max_age_s: float = 900.0
    drain_timeout_s: float = 10.0
    # Cross-host hand-off: backend kind + epoch-fenced writer lease
    # (ttl 0 = fencing off) + boot lease wait (0 = auto).
    snapshot_backend: str = "file"
    snapshot_lease_ttl_s: float = 0.0
    snapshot_lease_wait_s: float = 0.0
    # Post-restart resync pacing + boot-time roster pre-stacking.
    resync_max_inflight: int = 8
    recovery_prestack: bool = False
    # Resident-state scrubber cadence (utils/scrub); 0 disables.
    scrub_interval_s: float = 30.0
    # Federated multi-cluster assignment (federated/): peer identity,
    # peer set (validated "id=host:port" list), round/timeout bounds,
    # and the last-good dual cache's staleness window.
    federation_self_id: Optional[str] = None
    federation_peers: str = ""
    federation_rounds: int = 16
    federation_sync_timeout_s: float = 2.0
    federation_max_staleness_s: float = 300.0
    federation_gossip_interval_s: float = 0.0
    federation_capacity: Optional[list] = None
    # (max_partitions, num_consumers) shapes to pre-compile at configure().
    warmup_shapes: list = field(default_factory=list)
    consumer_group_props: Dict[str, Any] = field(default_factory=dict)
    metadata_consumer_props: Dict[str, Any] = field(default_factory=dict)

    @property
    def client_id(self) -> str:
        return f"{self.group_id}.assignor"


def _as_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() in ("true", "1", "yes")


def parse_config(configs: Mapping[str, Any]) -> AssignorConfig:
    """Validate and type the raw config map.

    Raises ``ValueError`` if ``group.id`` is absent — the reference throws
    IllegalArgumentException in the same situation (:107-113) so that a
    misconfigured consumer fails at construction, not mid-rebalance.
    """
    consumer_group_props = dict(configs)

    group_id = consumer_group_props.get(GROUP_ID_CONFIG)
    if group_id is None:
        raise ValueError(
            f"{GROUP_ID_CONFIG} cannot be null when using "
            f"{PARTITION_ASSIGNMENT_STRATEGY_CONFIG}=LagBasedPartitionAssignor"
        )

    solver = str(consumer_group_props.get(SOLVER_CONFIG, "rounds"))
    if solver not in VALID_SOLVERS:
        raise ValueError(
            f"{SOLVER_CONFIG}={solver!r} invalid; choose one of {VALID_SOLVERS}"
        )

    # Derived metadata-consumer properties, exactly as the reference builds
    # them (:116-120): same config, auto-commit off, suffixed client id.
    metadata_consumer_props = dict(consumer_group_props)
    metadata_consumer_props[ENABLE_AUTO_COMMIT_CONFIG] = "false"
    metadata_consumer_props[CLIENT_ID_CONFIG] = f"{group_id}.assignor"

    def _as_int(key: str, default: int, minimum: int) -> int:
        raw = consumer_group_props.get(key, default)
        try:
            value = int(raw)
        except (TypeError, ValueError):
            raise ValueError(f"{key}={raw!r} is not an integer")
        if value < minimum:
            raise ValueError(f"{key}={value} must be >= {minimum}")
        return value

    sinkhorn_iters = _as_int(SINKHORN_ITERS_CONFIG, 24, 1)
    raw_refine = consumer_group_props.get(REFINE_ITERS_CONFIG, None)
    refine_iters = (
        None
        if raw_refine in (None, "", "auto")
        else _as_int(REFINE_ITERS_CONFIG, raw_refine, 0)
    )
    if solver == "global" and refine_iters:
        raise ValueError(
            f"{REFINE_ITERS_CONFIG} is per-topic and would undo the "
            f"'global' solver's cross-topic balance; unset it or choose "
            f"solver 'rounds'/'scan'/'sinkhorn'"
        )

    quality_mode = str(
        consumer_group_props.get(QUALITY_MODE_CONFIG, "auto")
    )
    if quality_mode not in QUALITY_MODES:
        raise ValueError(
            f"{QUALITY_MODE_CONFIG}={quality_mode!r} invalid; choose "
            f"one of {QUALITY_MODES}"
        )
    raw_tile = consumer_group_props.get(QUALITY_TILE_CONFIG, 1024)
    try:
        quality_tile = validate_quality_tile(raw_tile)
    except ValueError as exc:
        raise ValueError(f"{QUALITY_TILE_CONFIG}: {exc}")

    raw_shapes = consumer_group_props.get(WARMUP_SHAPES_CONFIG, "")
    warmup_shapes = []
    if raw_shapes not in (None, ""):
        try:
            warmup_shapes = parse_warmup_shapes(raw_shapes)
        except ValueError as exc:
            raise ValueError(f"{WARMUP_SHAPES_CONFIG}: {exc}")

    raw_timeout = consumer_group_props.get(SOLVE_TIMEOUT_CONFIG, 120_000)
    try:
        timeout_ms = float(raw_timeout) if raw_timeout not in ("", None) else 0.0
    except (TypeError, ValueError):
        raise ValueError(
            f"{SOLVE_TIMEOUT_CONFIG}={raw_timeout!r} is not a number"
        )
    solve_timeout_s = timeout_ms / 1000.0 if timeout_ms > 0 else None

    def _as_ms(key: str, default_ms: float) -> float:
        raw = consumer_group_props.get(key, default_ms)
        try:
            value = float(raw)
        except (TypeError, ValueError):
            raise ValueError(f"{key}={raw!r} is not a number")
        if value < 0:
            raise ValueError(f"{key}={value} must be >= 0")
        return value / 1000.0

    metrics_port = _as_int(METRICS_PORT_CONFIG, 0, 0)

    raw_snap_path = consumer_group_props.get(SNAPSHOT_PATH_CONFIG, "")
    snapshot_path = (
        str(raw_snap_path) if raw_snap_path not in (None, "") else None
    )
    snapshot_interval_s = _as_ms(SNAPSHOT_INTERVAL_CONFIG, 30_000.0)
    if snapshot_interval_s <= 0:
        raise ValueError(
            f"{SNAPSHOT_INTERVAL_CONFIG} must be > 0 ms"
        )
    snapshot_max_age_s = _as_ms(SNAPSHOT_MAX_AGE_CONFIG, 900_000.0)
    if snapshot_max_age_s <= 0:
        raise ValueError(f"{SNAPSHOT_MAX_AGE_CONFIG} must be > 0 ms")
    drain_timeout_s = _as_ms(DRAIN_TIMEOUT_CONFIG, 10_000.0)

    # Cross-host hand-off knobs: backend kind validated against the
    # roster utils/snapshot ships (a typo'd backend fails at
    # configure() time, not at the first snapshot write).
    from .snapshot import BACKEND_KINDS

    snapshot_backend = str(
        consumer_group_props.get(SNAPSHOT_BACKEND_CONFIG, "file")
    )
    if snapshot_backend not in BACKEND_KINDS:
        raise ValueError(
            f"{SNAPSHOT_BACKEND_CONFIG}={snapshot_backend!r} invalid; "
            f"choose one of {list(BACKEND_KINDS)}"
        )
    snapshot_lease_ttl_s = _as_ms(SNAPSHOT_LEASE_TTL_CONFIG, 0.0)
    snapshot_lease_wait_s = _as_ms(SNAPSHOT_LEASE_WAIT_CONFIG, 0.0)
    resync_max_inflight = _as_int(RESYNC_MAX_INFLIGHT_CONFIG, 8, 0)
    scrub_interval_s = _as_ms(SCRUB_INTERVAL_CONFIG, 30_000.0)

    # Federation knobs: the peer list is PARSED here so a typo'd spec
    # fails at configure() time, not at the first peer round.
    raw_self_id = consumer_group_props.get(FEDERATION_SELF_ID_CONFIG, "")
    federation_self_id = (
        str(raw_self_id) if raw_self_id not in (None, "") else None
    )
    federation_peers = str(
        consumer_group_props.get(FEDERATION_PEERS_CONFIG, "") or ""
    )
    if federation_peers:
        if federation_self_id is None:
            raise ValueError(
                f"{FEDERATION_PEERS_CONFIG} requires "
                f"{FEDERATION_SELF_ID_CONFIG}"
            )
        from ..federated.peers import parse_peer_specs

        try:
            parse_peer_specs(federation_peers)
        except ValueError as exc:
            raise ValueError(f"{FEDERATION_PEERS_CONFIG}: {exc}")
    federation_rounds = _as_int(FEDERATION_ROUNDS_CONFIG, 16, 1)
    federation_sync_timeout_s = _as_ms(
        FEDERATION_SYNC_TIMEOUT_CONFIG, 2_000.0
    )
    if federation_sync_timeout_s <= 0:
        raise ValueError(f"{FEDERATION_SYNC_TIMEOUT_CONFIG} must be > 0 ms")
    federation_max_staleness_s = _as_ms(
        FEDERATION_MAX_STALENESS_CONFIG, 300_000.0
    )
    federation_gossip_interval_s = _as_ms(
        FEDERATION_GOSSIP_INTERVAL_CONFIG, 0.0
    )
    if federation_gossip_interval_s < 0:
        raise ValueError(
            f"{FEDERATION_GOSSIP_INTERVAL_CONFIG} must be >= 0 ms"
        )
    raw_capacity = consumer_group_props.get(
        FEDERATION_CAPACITY_CONFIG, ""
    )
    federation_capacity = None
    if raw_capacity not in (None, ""):
        try:
            federation_capacity = [
                float(v) for v in str(raw_capacity).split(",")
            ]
        except ValueError:
            raise ValueError(
                f"{FEDERATION_CAPACITY_CONFIG}={raw_capacity!r} must be "
                "comma-separated numbers"
            )
        if any(v <= 0 for v in federation_capacity):
            raise ValueError(
                f"{FEDERATION_CAPACITY_CONFIG} entries must be > 0"
            )

    # SLO class map + per-class deadline budgets: prefix-keyed entries,
    # validated against the class roster (utils/overload) so a typo'd
    # class fails at configure() time, not mid-stampede.
    from .overload import SLO_CLASSES

    slo_classes: Dict[str, str] = {}
    slo_deadline_s: Dict[str, float] = {}
    for key, value in consumer_group_props.items():
        if key.startswith(SLO_CLASS_PREFIX):
            stream_id = key[len(SLO_CLASS_PREFIX):]
            klass = str(value)
            if not stream_id or klass not in SLO_CLASSES:
                raise ValueError(
                    f"{key}={value!r} invalid; classes: {list(SLO_CLASSES)}"
                )
            slo_classes[stream_id] = klass
        elif key.startswith(SLO_DEADLINE_PREFIX):
            klass = key[len(SLO_DEADLINE_PREFIX):]
            if klass not in SLO_CLASSES:
                raise ValueError(
                    f"{key}: unknown class {klass!r}; "
                    f"classes: {list(SLO_CLASSES)}"
                )
            secs = _as_ms(key, 0.0)  # ms-typed knob, seconds out
            if secs <= 0:
                raise ValueError(f"{key}={value!r} must be > 0 ms")
            slo_deadline_s[klass] = secs

    # Delta-epoch knobs: the fraction is a plain float in (0, 1]; the
    # bucket count bounds the per-shape executable ladder (a typo'd
    # 10_000 here would mint thousands of compiles, so it is capped).
    raw_frac = consumer_group_props.get(DELTA_MAX_FRACTION_CONFIG, 0.125)
    try:
        delta_max_fraction = float(raw_frac)
    except (TypeError, ValueError):
        raise ValueError(
            f"{DELTA_MAX_FRACTION_CONFIG}={raw_frac!r} is not a number"
        )
    if not 0.0 < delta_max_fraction <= 1.0:
        raise ValueError(
            f"{DELTA_MAX_FRACTION_CONFIG}={delta_max_fraction} must be "
            "in (0, 1]"
        )
    delta_buckets = _as_int(DELTA_BUCKETS_CONFIG, 6, 0)
    if delta_buckets > 16:
        raise ValueError(
            f"{DELTA_BUCKETS_CONFIG}={delta_buckets} must be <= 16 "
            "(each rung is one compiled executable per shape bucket)"
        )

    # Mesh knobs: the spec is validated HERE (the sharded/ parser) so a
    # typo'd device count fails at configure() time, not at boot.
    from ..sharded.mesh import _parse_shape as _parse_mesh_shape
    from ..sharded.mesh import _parse_spec as _parse_mesh_spec

    raw_mesh = consumer_group_props.get(MESH_DEVICES_CONFIG, "off")
    try:
        mesh_devices = str(_parse_mesh_spec(raw_mesh))
    except ValueError as exc:
        raise ValueError(f"{MESH_DEVICES_CONFIG}: {exc}")
    mesh_solve_min_rows = _as_int(
        MESH_SOLVE_MIN_ROWS_CONFIG, 65536, 1
    )
    raw_shape = consumer_group_props.get(MESH_SHAPE_CONFIG, "off")
    try:
        shape = _parse_mesh_shape(raw_shape)
    except ValueError as exc:
        raise ValueError(f"{MESH_SHAPE_CONFIG}: {exc}")
    mesh_shape = shape if isinstance(shape, str) else f"{shape[0]}x{shape[1]}"

    # The controller keeps this knob in ms (it normalizes a p99 that is
    # measured in ms), so convert _as_ms's seconds back out once, here.
    overload_latency_budget_ms = (
        _as_ms(OVERLOAD_LATENCY_BUDGET_CONFIG, 0.0) * 1000.0
    )
    raw_depth = consumer_group_props.get(OVERLOAD_DEPTH_HIGH_CONFIG, 24.0)
    try:
        overload_depth_high = float(raw_depth)
    except (TypeError, ValueError):
        raise ValueError(
            f"{OVERLOAD_DEPTH_HIGH_CONFIG}={raw_depth!r} is not a number"
        )
    if overload_depth_high <= 0:
        raise ValueError(
            f"{OVERLOAD_DEPTH_HIGH_CONFIG}={overload_depth_high} must be > 0"
        )

    return AssignorConfig(
        group_id=str(group_id),
        auto_offset_reset=str(
            consumer_group_props.get(AUTO_OFFSET_RESET_CONFIG, "latest")
        ),
        solver=solver,
        host_fallback=_as_bool(consumer_group_props.get(FALLBACK_CONFIG, True)),
        profile=_as_bool(consumer_group_props.get(PROFILE_CONFIG, False)),
        solve_timeout_s=solve_timeout_s,
        breaker_cooldown_s=_as_ms(BREAKER_COOLDOWN_CONFIG, 300_000.0),
        breaker_failures=_as_int(BREAKER_FAILURES_CONFIG, 3, 1),
        lag_retries=_as_int(LAG_RETRIES_CONFIG, 0, 0),
        lag_retry_backoff_s=_as_ms(LAG_RETRY_BACKOFF_CONFIG, 50.0),
        sinkhorn_iters=sinkhorn_iters,
        refine_iters=refine_iters,
        quality_mode=quality_mode,
        quality_tile=quality_tile,
        coalesce_window_s=_as_ms(COALESCE_WINDOW_CONFIG, 0.5),
        coalesce_max_batch=_as_int(COALESCE_MAX_BATCH_CONFIG, 32, 1),
        coalesce_lock_waves=_as_int(COALESCE_LOCK_WAVES_CONFIG, 1, 1),
        coalesce_pipeline=_as_bool(
            consumer_group_props.get(COALESCE_PIPELINE_CONFIG, True)
        ),
        delta_enabled=_as_bool(
            consumer_group_props.get(DELTA_ENABLED_CONFIG, True)
        ),
        delta_max_fraction=delta_max_fraction,
        delta_buckets=delta_buckets,
        delta_adaptive=_as_bool(
            consumer_group_props.get(DELTA_ADAPTIVE_CONFIG, True)
        ),
        mesh_devices=mesh_devices,
        mesh_solve_min_rows=mesh_solve_min_rows,
        mesh_shape=mesh_shape,
        slo_classes=slo_classes,
        slo_deadline_s=slo_deadline_s,
        overload_latency_budget_ms=overload_latency_budget_ms,
        overload_depth_high=overload_depth_high,
        metrics_port=metrics_port if metrics_port > 0 else None,
        snapshot_path=snapshot_path,
        snapshot_interval_s=snapshot_interval_s,
        snapshot_max_age_s=snapshot_max_age_s,
        drain_timeout_s=drain_timeout_s,
        snapshot_backend=snapshot_backend,
        snapshot_lease_ttl_s=snapshot_lease_ttl_s,
        snapshot_lease_wait_s=snapshot_lease_wait_s,
        resync_max_inflight=resync_max_inflight,
        scrub_interval_s=scrub_interval_s,
        federation_self_id=federation_self_id,
        federation_peers=federation_peers,
        federation_rounds=federation_rounds,
        federation_sync_timeout_s=federation_sync_timeout_s,
        federation_max_staleness_s=federation_max_staleness_s,
        federation_gossip_interval_s=federation_gossip_interval_s,
        federation_capacity=federation_capacity,
        recovery_prestack=_as_bool(
            consumer_group_props.get(RECOVERY_PRESTACK_CONFIG, False)
        ),
        warmup_shapes=warmup_shapes,
        consumer_group_props=consumer_group_props,
        metadata_consumer_props=metadata_consumer_props,
    )
